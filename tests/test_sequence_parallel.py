"""Ring attention and Ulysses sequence parallelism: distributed outputs
and gradients must match single-device full attention exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import (
    heads_to_seq,
    ring_attention,
    seq_to_heads,
    ulysses_attention,
)

B, H, D = 2, 8, 4  # batch, heads, head_dim


def reference_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(D)
    logits = np.einsum("bqhd,bkhd->bhqk", q * scale, k).astype(np.float64)
    if causal:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), bool))
        logits = np.where(mask[None, None], logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


def make_qkv(seq, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((B, seq, H, D)).astype(np.float32)
            for _ in range(3)]


def run_sharded(fn, q, k, v, causal):
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(None, axis))
    sharded = jax.jit(jax.shard_map(
        lambda q, k, v: fn(q, k, v, axis, causal=causal),
        mesh=mesh, in_specs=(P(None, axis),) * 3,
        out_specs=P(None, axis), check_vma=False))
    args = [jax.device_put(t, sharding) for t in (q, k, v)]
    return np.asarray(sharded(*args))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    n = hvd.size()
    q, k, v = make_qkv(4 * n)
    out = run_sharded(ring_attention, q, k, v, causal)
    expect = reference_attention(q, k, v, causal)
    assert np.allclose(out, expect, rtol=2e-4, atol=2e-5), \
        np.abs(out - expect).max()


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_full(causal):
    n = hvd.size()
    q, k, v = make_qkv(2 * n, seed=1)
    out = run_sharded(ulysses_attention, q, k, v, causal)
    expect = reference_attention(q, k, v, causal)
    assert np.allclose(out, expect, rtol=2e-4, atol=2e-5), \
        np.abs(out - expect).max()


def test_seq_head_switch_round_trip():
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    x = np.arange(B * 4 * n * H * D, dtype=np.float32).reshape(B, 4 * n, H, D)

    fn = jax.jit(jax.shard_map(
        lambda x: heads_to_seq(seq_to_heads(x, axis), axis),
        mesh=mesh, in_specs=P(None, axis), out_specs=P(None, axis),
        check_vma=False))
    out = np.asarray(fn(jax.device_put(
        x, NamedSharding(mesh, P(None, axis)))))
    assert np.allclose(out, x)


def test_seq_to_heads_layout():
    """After the switch each chip holds the FULL sequence of its head
    group (the Ulysses contract)."""
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    seq = 2 * n
    x = np.zeros((1, seq, H, D), np.float32)
    for s in range(seq):
        for h in range(H):
            x[0, s, h, 0] = s * 100 + h

    fn = jax.jit(jax.shard_map(
        lambda x: seq_to_heads(x, axis), mesh=mesh,
        in_specs=P(None, axis), out_specs=P(None, None, axis),
        check_vma=False))
    out = np.asarray(fn(jax.device_put(
        x, NamedSharding(mesh, P(None, axis)))))
    assert out.shape == (1, seq, H, D)
    assert np.allclose(out[0, :, :, 0],
                       x[0, :, :, 0])  # global view reassembles exactly


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_gradients_match(causal):
    """d(loss)/d(q,k,v) through the ring must equal the full-attention
    gradients — the schedule must be trainable, not just forward-correct.
    Both mask modes: the re-rotating backward has distinct causal (masked
    + cond-skipped blocks) and non-causal branches."""
    n = hvd.size()
    q, k, v = make_qkv(2 * n, seed=2)
    tgt = np.random.default_rng(3).standard_normal(q.shape).astype(np.float32)
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(None, axis))

    def ring_loss(q, k, v, t):
        out = ring_attention(q, k, v, axis, causal=causal)
        return jnp.sum((out - t) ** 2)

    grad_fn = jax.jit(jax.shard_map(
        lambda q, k, v, t: jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v, t),
        mesh=mesh, in_specs=(P(None, axis),) * 4,
        out_specs=(P(None, axis),) * 3, check_vma=False))
    gq, gk, gv = [np.asarray(g) for g in grad_fn(
        *[jax.device_put(t, sharding) for t in (q, k, v, tgt)])]

    def full_loss(q, k, v):
        scale = 1.0 / jnp.sqrt(D)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
        if causal:
            s = q.shape[1]
            mask = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(mask[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum((out - tgt) ** 2)

    eq, ek, ev = jax.grad(full_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    assert np.allclose(gq, eq, rtol=1e-3, atol=1e-4), np.abs(gq - eq).max()
    assert np.allclose(gk, ek, rtol=1e-3, atol=1e-4), np.abs(gk - ek).max()
    assert np.allclose(gv, ev, rtol=1e-3, atol=1e-4), np.abs(gv - ev).max()


def test_ulysses_rejects_indivisible_heads():
    if hvd.size() == 1:
        pytest.skip("needs multi-device")
    mesh, axis = hvd.mesh(), hvd.axis_name()
    n = hvd.size()
    x = jnp.zeros((1, n, H + 1, D))  # H+1 heads not divisible by n

    with pytest.raises(Exception, match="divide"):
        jax.jit(jax.shard_map(
            lambda x: seq_to_heads(x, axis), mesh=mesh,
            in_specs=P(None, axis), out_specs=P(None, None, axis),
            check_vma=False))(x)


@pytest.mark.parametrize("mode", ["ring", "ring_zigzag", "ulysses"])
def test_transformer_lm_sequence_parallel_matches_full(mode):
    """TransformerLM(attn_mode=ring/ulysses) under shard_map over the
    sequence axis produces the same logits as full attention on the whole
    sequence (positions offset per block, causal across blocks)."""
    from horovod_tpu.models import TransformerConfig, TransformerLM

    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    seq = 2 * n
    base = dict(vocab_size=64, num_layers=2, num_heads=H, d_model=32,
                d_ff=64, max_seq_len=seq, dtype=jnp.float32)
    full_model = TransformerLM(TransformerConfig(**base))
    sp_model = TransformerLM(TransformerConfig(**base, attn_mode=mode,
                                               seq_axis=axis))
    tokens = np.random.default_rng(0).integers(0, 64, (2, seq))
    params = full_model.init(jax.random.PRNGKey(0),
                             jnp.asarray(tokens))["params"]

    expect = np.asarray(full_model.apply({"params": params},
                                         jnp.asarray(tokens)))

    fn = jax.jit(jax.shard_map(
        lambda p, t: sp_model.apply({"params": p}, t),
        mesh=mesh, in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis), check_vma=False))
    out = np.asarray(fn(params, jax.device_put(
        tokens, NamedSharding(mesh, P(None, axis)))))
    assert np.allclose(out, expect, rtol=2e-3, atol=2e-4), \
        np.abs(out - expect).max()


def test_ulysses_attention_gradients_match():
    """Backward through the all-to-all switches equals full-attention
    gradients (same contract as the ring test)."""
    n = hvd.size()
    q, k, v = make_qkv(2 * n, seed=4)
    tgt = np.random.default_rng(5).standard_normal(q.shape).astype(np.float32)
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(None, axis))

    def ulysses_loss(q, k, v, t):
        out = ulysses_attention(q, k, v, axis, causal=True)
        return jnp.sum((out - t) ** 2)

    grad_fn = jax.jit(jax.shard_map(
        lambda q, k, v, t: jax.grad(ulysses_loss, argnums=(0, 1, 2))(
            q, k, v, t),
        mesh=mesh, in_specs=(P(None, axis),) * 4,
        out_specs=(P(None, axis),) * 3, check_vma=False))
    gq, gk, gv = [np.asarray(g) for g in grad_fn(
        *[jax.device_put(t, sharding) for t in (q, k, v, tgt)])]

    def full_loss(q, k, v):
        scale = 1.0 / jnp.sqrt(D)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum((out - tgt) ** 2)

    eq, ek, ev = jax.grad(full_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    assert np.allclose(gq, eq, rtol=1e-3, atol=1e-4), np.abs(gq - eq).max()
    assert np.allclose(gk, ek, rtol=1e-3, atol=1e-4), np.abs(gk - ek).max()
    assert np.allclose(gv, ev, rtol=1e-3, atol=1e-4), np.abs(gv - ev).max()


def test_ring_attention_residuals_are_o_block():
    """The custom VJP must save only the home blocks + (out, lse) — no
    per-step rotated K/V (that was the round-3 O(sequence) memory gap).
    Checked two ways: the fwd rule's residual tree is exactly 5 O(block)
    arrays, and jax's own saved-residual report for a grad through the
    ring contains no more total bytes than a constant multiple of the
    block size (independent of ring length)."""
    from horovod_tpu.parallel.sequence import _ring_core_fwd

    n = hvd.size()
    if n == 1:
        pytest.skip("needs multi-device")
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sq = 4
    bh, d = B * H, D

    def fwd_residuals(qf, kf, vf):
        _, res = _ring_core_fwd(qf, kf, vf, axis, True, False, False)
        return res

    shapes = jax.eval_shape(
        jax.shard_map(fwd_residuals, mesh=mesh,
                      in_specs=(P(None, axis),) * 3,
                      out_specs=P(None, axis), check_vma=False),
        *[jax.ShapeDtypeStruct((bh, sq * n, d), jnp.float32)] * 3)
    leaves = jax.tree_util.tree_leaves(shapes)
    assert len(leaves) == 5  # qf, kf, vf, out, lse — nothing per-step
    # eval_shape reports the GLOBAL view: each per-device residual is one
    # block, so globally a leaf is at most one full (bh, seq, d) tensor; a
    # per-step saver would show ~n K/V-shaped leaves instead of exactly 5.
    global_elems = bh * (sq * n) * d
    for leaf in leaves:
        assert np.prod(leaf.shape) <= global_elems, leaf.shape

    # independent check through jax.grad itself: total residual bytes for
    # the whole ring loss must not grow with n (no per-step K/V pinned)
    from jax._src.ad_checkpoint import saved_residuals
    q, k, v = make_qkv(sq * n, seed=7)

    def loss(q, k, v):
        out = ring_attention(q, k, v, axis, causal=True)
        return jnp.sum(out ** 2)

    res = saved_residuals(
        jax.shard_map(loss, mesh=mesh, in_specs=(P(None, axis),) * 3,
                      out_specs=P(), check_vma=False),
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    total = sum(int(np.prod(r[0].shape)) for r in res
                if hasattr(r[0], "shape"))
    # home q/k/v + out (4 * block * B*H*D) + lse + slop; a per-step saver
    # would be ~n x larger. Budget: 6 block-sized tensors.
    assert total <= 6 * B * (sq * n) * H * D, total


# --- pallas flash kernel path (interpret mode on CPU) ----------------------

@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_pallas_matches_jnp(causal):
    n = hvd.size()
    q, k, v = make_qkv(2 * n, seed=6)
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(None, axis))
    outs = {}
    for pallas in (False, True):
        fn = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(
                q, k, v, axis, causal=causal, use_pallas=pallas,
                interpret=pallas),
            mesh=mesh, in_specs=(P(None, axis),) * 3,
            out_specs=P(None, axis), check_vma=False))
        outs[pallas] = np.asarray(fn(
            *[jax.device_put(t, sharding) for t in (q, k, v)]))
    assert np.allclose(outs[True], outs[False], rtol=1e-5, atol=1e-6), \
        np.abs(outs[True] - outs[False]).max()
    expect = reference_attention(q, k, v, causal)
    assert np.allclose(outs[True], expect, rtol=2e-4, atol=2e-5)


def test_ring_attention_pallas_gradients():
    """custom_vjp through the kernel: grads equal the jnp path's."""
    n = hvd.size()
    q, k, v = make_qkv(2 * n, seed=7)
    tgt = np.random.default_rng(8).standard_normal(q.shape).astype(np.float32)
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(None, axis))
    grads = {}
    for pallas in (False, True):
        def loss(q, k, v, t, pallas=pallas):
            out = ring_attention(q, k, v, axis, causal=True,
                                 use_pallas=pallas, interpret=pallas)
            return jnp.sum((out - t) ** 2)

        fn = jax.jit(jax.shard_map(
            lambda q, k, v, t: jax.grad(loss, argnums=(0, 1, 2))(q, k, v, t),
            mesh=mesh, in_specs=(P(None, axis),) * 4,
            out_specs=(P(None, axis),) * 3, check_vma=False))
        grads[pallas] = [np.asarray(g) for g in fn(
            *[jax.device_put(t, sharding) for t in (q, k, v, tgt)])]
    for gp, gj in zip(grads[True], grads[False]):
        assert np.allclose(gp, gj, rtol=1e-4, atol=1e-5), np.abs(gp - gj).max()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_q_tiling(monkeypatch, causal):
    """Multiple q tiles per invocation (grid dim 1) must match the
    single-tile jnp formulation exactly — the per-q-tile scratch carry
    init/flush is the subtle part."""
    from horovod_tpu.ops import flash

    monkeypatch.setattr(flash, "DEFAULT_Q_TILE", 4)
    monkeypatch.setattr(flash, "DEFAULT_KV_TILE", 8)
    bh, sq, d = 3, 16, 8  # 4 q-tiles x 2 kv-tiles
    rng = np.random.default_rng(11)
    q, k, v = [jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
               for _ in range(3)]
    m = jnp.full((bh, sq, 1), flash.NEG_INF, jnp.float32)
    l = jnp.zeros((bh, sq, 1), jnp.float32)
    acc = jnp.zeros((bh, sq, d), jnp.float32)
    zero = jnp.asarray(0, jnp.int32)
    mk, lk, ak = flash.block_attend(q, k, v, zero, zero, causal, True,
                                    m, l, acc)
    mj, lj, aj = flash._attend_jnp(q, k, v, zero, zero, causal, m, l, acc)
    for got, want in ((mk, mj), (lk, lj), (ak, aj)):
        assert np.allclose(np.asarray(got), np.asarray(want),
                           rtol=1e-5, atol=1e-6), \
            np.abs(np.asarray(got) - np.asarray(want)).max()


def test_flash_kernel_compiled_on_tpu():
    """Compiled (non-interpret) Mosaic kernel vs jnp formulation — runs
    only when the suite executes on a real TPU (verified manually on v5e;
    this keeps a CI signal wherever TPU hardware is present)."""
    from horovod_tpu.ops import flash

    if jax.default_backend() != "tpu":
        pytest.skip("needs TPU for the compiled Mosaic kernel")
    rng = np.random.default_rng(0)
    bh, sq, d = 4, 256, 128
    q = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    m = jnp.full((bh, sq, 1), -1e30, jnp.float32)
    l = jnp.zeros((bh, sq, 1), jnp.float32)
    acc = jnp.zeros((bh, sq, d), jnp.float32)
    z = jnp.asarray(0, jnp.int32)
    got = flash.block_attend(q, k, v, z, z, True, False, m, l, acc)
    ref = flash._attend_jnp(q, k, v, z, z, True, m, l, acc)
    out_got = np.asarray(got[2] / jnp.maximum(got[1], 1e-30))
    out_ref = np.asarray(ref[2] / jnp.maximum(ref[1], 1e-30))
    assert np.allclose(out_got, out_ref, rtol=1e-3, atol=1e-3)


def test_ulysses_blockwise_local_attention():
    """The jnp fallback's chunked local attention equals the one-shot
    softmax (no O(s^2) logits needed for correctness)."""
    from horovod_tpu.parallel.sequence import _local_flash

    rng = np.random.default_rng(9)
    q, k, v = [jnp.asarray(rng.standard_normal((2, 64, H, D)), jnp.float32)
               for _ in range(3)]
    out = np.asarray(_local_flash(q, k, v, True, False, False, kv_chunk=16))
    expect = reference_attention(np.asarray(q), np.asarray(k), np.asarray(v),
                                 True)
    assert np.allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_flash_tile_pad_bounds_ragged_sizes():
    """Ragged dims keep the DEFAULT tile and pad to the next tile boundary
    — a divisor search would hand a prime size a tile of 1 (1-row MXU
    grid, ADVICE r4) and a whole-dimension fallback would unbound VMEM."""
    from horovod_tpu.ops.flash import _tile_pad

    assert _tile_pad(16, 1024) == (16, 16)        # small: one aligned tile
    assert _tile_pad(4096, 1024) == (1024, 4096)  # exact multiple
    assert _tile_pad(12, 1024) == (16, 16)        # small ragged: 8-aligned
    assert _tile_pad(7919, 1024) == (256, 7936)   # prime: pad, NOT tile=1
    # just past a boundary: a halved tile cuts the padding waste ~4x
    assert _tile_pad(1025, 1024) == (256, 1280)
    assert _tile_pad(1536, 1024) == (512, 1536)   # exact at a halving


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_kernel_awkward_sizes(causal):
    """Prime-ish sq/sk exercise the pad-and-mask path: padded kv columns
    must not leak into (m, l, acc) and padded q rows are sliced off."""
    from horovod_tpu.ops import flash

    bh, sq, sk, d = 2, 13, 11, 8  # neither a multiple of anything useful
    rng = np.random.default_rng(31)
    q = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((bh, sk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((bh, sk, d)), jnp.float32)
    m = jnp.full((bh, sq, 1), flash.NEG_INF, jnp.float32)
    l = jnp.zeros((bh, sq, 1), jnp.float32)
    acc = jnp.zeros((bh, sq, d), jnp.float32)
    qpos0 = jnp.asarray(3, jnp.int32)
    kpos0 = jnp.asarray(0, jnp.int32)
    got = flash.block_attend(q, k, v, qpos0, kpos0, causal, True, m, l, acc)
    want = flash._attend_jnp(q, k, v, qpos0, kpos0, causal, m, l, acc)
    for name, g, w in zip(("m", "l", "acc"), got, want):
        assert g.shape == w.shape, name
        assert np.allclose(np.asarray(g), np.asarray(w),
                           rtol=1e-5, atol=1e-5), name


@pytest.mark.parametrize("causal", [True, False])
def test_flash_backward_kernel_awkward_sizes(monkeypatch, causal):
    """flash_block_grads with non-tile-aligned sq/sk: the padded tail of
    kv is masked and padded q rows carry zero dout, so gradients match
    the unpadded jnp identities exactly."""
    from horovod_tpu.ops import flash

    monkeypatch.setattr(flash, "DEFAULT_Q_TILE", 8)
    monkeypatch.setattr(flash, "DEFAULT_KV_TILE", 8)
    bh, sq, sk, d = 2, 13, 11, 8  # pads to 16 q x 16 kv
    rng = np.random.default_rng(37)
    q = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((bh, sk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((bh, sk, d)), jnp.float32)
    dout = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    qpos0 = jnp.asarray(2, jnp.int32)
    kpos0 = jnp.asarray(0, jnp.int32)
    m = jnp.full((bh, sq, 1), flash.NEG_INF, jnp.float32)
    l = jnp.zeros((bh, sq, 1), jnp.float32)
    acc = jnp.zeros((bh, sq, d), jnp.float32)
    m1, l1, acc1 = flash._attend_jnp(q, k, v, qpos0, kpos0, causal,
                                     m, l, acc)
    l_safe = jnp.maximum(l1, 1e-30)
    lse = m1 + jnp.log(l_safe)
    D = jnp.sum(dout * (acc1 / l_safe), axis=-1, keepdims=True)
    got = flash.flash_block_grads(q, k, v, lse, dout, D, qpos0, kpos0,
                                  causal, interpret=True)
    want = flash.jnp_block_grads(q, k, v, lse, dout, D, qpos0, kpos0, causal)
    for name, g, w in zip(("dq", "dk", "dv"), got, want):
        assert g.shape == w.shape, name
        assert np.allclose(np.asarray(g), np.asarray(w),
                           rtol=1e-4, atol=1e-4), \
            (name, np.abs(np.asarray(g) - np.asarray(w)).max())


@pytest.mark.parametrize("causal", [True, False])
def test_flash_backward_kernel_multi_tile(monkeypatch, causal):
    """flash_block_grads (pallas dq + dkv kernels) must match the jnp
    backward identities across multiple q AND kv tiles, including the
    per-tile scratch accumulate/flush in both sweep orders."""
    from horovod_tpu.ops import flash

    monkeypatch.setattr(flash, "DEFAULT_Q_TILE", 4)
    monkeypatch.setattr(flash, "DEFAULT_KV_TILE", 4)
    bh, sq, sk, d = 2, 12, 8, 8  # 3 q-tiles x 2 kv-tiles
    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((bh, sk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((bh, sk, d)), jnp.float32)
    dout = jnp.asarray(rng.standard_normal((bh, sq, d)), jnp.float32)
    qpos0 = jnp.asarray(4, jnp.int32)   # offset blocks, like a ring step
    kpos0 = jnp.asarray(0, jnp.int32)

    # forward stats via the jnp formulation
    m = jnp.full((bh, sq, 1), flash.NEG_INF, jnp.float32)
    l = jnp.zeros((bh, sq, 1), jnp.float32)
    acc = jnp.zeros((bh, sq, d), jnp.float32)
    m1, l1, acc1 = flash._attend_jnp(q, k, v, qpos0, kpos0, causal,
                                     m, l, acc)
    l_safe = jnp.maximum(l1, 1e-30)
    out = acc1 / l_safe
    lse = m1 + jnp.log(l_safe)
    D = jnp.sum(dout * out, axis=-1, keepdims=True)

    got = flash.flash_block_grads(q, k, v, lse, dout, D, qpos0, kpos0,
                                  causal, interpret=True)

    # jnp reference: the identities from _ring_core_bwd
    s = jnp.einsum("bqd,bkd->bqk", q, k)
    if causal:
        s = flash.causal_mask_scores(s, qpos0, kpos0)
    p = jnp.exp(s - lse)
    if causal:
        p = flash.zero_masked(p, s)
    dv_ref = jnp.einsum("bqk,bqd->bkd", p, dout)
    dp = jnp.einsum("bqd,bkd->bqk", dout, v)
    ds = p * (dp - D)
    dq_ref = jnp.einsum("bqk,bkd->bqd", ds, k)
    dk_ref = jnp.einsum("bqk,bqd->bkd", ds, q)
    for name, g, ref in (("dq", got[0], dq_ref), ("dk", got[1], dk_ref),
                         ("dv", got[2], dv_ref)):
        assert np.allclose(np.asarray(g), np.asarray(ref),
                           rtol=1e-5, atol=1e-5), \
            (name, np.abs(np.asarray(g) - np.asarray(ref)).max())


def test_ulysses_residuals_are_o_sequence_constant():
    """The local-flash custom VJP must save only (qf, kf, vf, out, lse)
    — five leaves, no O(s^2) logits in the residual tree."""
    from horovod_tpu.parallel.sequence import _local_flash_core_fwd

    bh, s, d = 4, 16, 8

    def fwd_residuals(qf, kf, vf):
        _, res = _local_flash_core_fwd(qf, kf, vf, True, False, False, 8)
        return res

    shapes = jax.eval_shape(
        fwd_residuals,
        *[jax.ShapeDtypeStruct((bh, s, d), jnp.float32)] * 3)
    leaves = jax.tree_util.tree_leaves(shapes)
    assert len(leaves) == 5
    for leaf in leaves:
        assert np.prod(leaf.shape) <= bh * s * d, leaf.shape  # never s^2


# -- zigzag schedule (causal load balance) ----------------------------------


def test_zigzag_shard_roundtrip():
    """zigzag_shard places rank r's halves at global chunks (r, 2n-1-r);
    unshard is its exact inverse."""
    from horovod_tpu.parallel.sequence import zigzag_shard, zigzag_unshard

    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    seq = 2 * n * 3  # chunk size 3
    x = np.arange(seq, dtype=np.float32).reshape(1, seq, 1)

    zz = jax.jit(jax.shard_map(lambda t: zigzag_shard(t, axis), mesh=mesh,
                               in_specs=P(None, axis),
                               out_specs=P(None, axis), check_vma=False))
    back = jax.jit(jax.shard_map(lambda t: zigzag_unshard(t, axis),
                                 mesh=mesh, in_specs=P(None, axis),
                                 out_specs=P(None, axis), check_vma=False))
    xs = jax.device_put(x, NamedSharding(mesh, P(None, axis)))
    z = zz(xs)
    # rank r's local block must be [chunk r, chunk 2n-1-r]
    zh = np.asarray(z).reshape(n, 2, 3)  # gathered: rank-major halves
    c = 3
    for r in range(n):
        assert np.allclose(zh[r, 0], np.arange(r * c, (r + 1) * c)), r
        hi = 2 * n - 1 - r
        assert np.allclose(zh[r, 1], np.arange(hi * c, (hi + 1) * c)), r
    assert np.allclose(np.asarray(back(z)), x)


def test_zigzag_ring_matches_full():
    n = hvd.size()
    q, k, v = make_qkv(4 * n, seed=11)
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(None, axis))
    sharded = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis, causal=True,
                                       schedule="zigzag"),
        mesh=mesh, in_specs=(P(None, axis),) * 3,
        out_specs=P(None, axis), check_vma=False))
    out = np.asarray(sharded(*[jax.device_put(t, sharding)
                               for t in (q, k, v)]))
    expect = reference_attention(q, k, v, True)
    assert np.allclose(out, expect, rtol=2e-4, atol=2e-5), \
        np.abs(out - expect).max()


@pytest.mark.parametrize("use_pallas", [False, True])
def test_zigzag_ring_gradients_match(use_pallas):
    """Zigzag gradients equal full-attention gradients through both the
    jnp and the Pallas (interpret) block-gradient paths."""
    n = hvd.size()
    q, k, v = make_qkv(2 * n, seed=12)
    tgt = np.random.default_rng(13).standard_normal(q.shape).astype(
        np.float32)
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(None, axis))

    def ring_loss(q, k, v, t):
        out = ring_attention(q, k, v, axis, causal=True, schedule="zigzag",
                             use_pallas=use_pallas, interpret=use_pallas)
        return jnp.sum((out - t) ** 2)

    grad_fn = jax.jit(jax.shard_map(
        lambda q, k, v, t: jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v,
                                                                  t),
        mesh=mesh, in_specs=(P(None, axis),) * 4,
        out_specs=(P(None, axis),) * 3, check_vma=False))
    gq, gk, gv = [np.asarray(g) for g in grad_fn(
        *[jax.device_put(t, sharding) for t in (q, k, v, tgt)])]

    def full_loss(q, k, v):
        scale = 1.0 / jnp.sqrt(D)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum((out - tgt) ** 2)

    eq, ek, ev = jax.grad(full_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    assert np.allclose(gq, eq, rtol=1e-3, atol=1e-4), np.abs(gq - eq).max()
    assert np.allclose(gk, ek, rtol=1e-3, atol=1e-4), np.abs(gk - ek).max()
    assert np.allclose(gv, ev, rtol=1e-3, atol=1e-4), np.abs(gv - ev).max()


def test_zigzag_rejects_bad_configs():
    mesh, axis = hvd.mesh(), hvd.axis_name()
    q, k, v = make_qkv(2 * hvd.size())
    with pytest.raises(ValueError, match="causal"):
        jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis, causal=False,
                                           schedule="zigzag"),
            mesh=mesh, in_specs=(P(None, axis),) * 3,
            out_specs=P(None, axis), check_vma=False)(q, k, v)
    with pytest.raises(ValueError, match="unknown ring schedule"):
        jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis,
                                           schedule="spiral"),
            mesh=mesh, in_specs=(P(None, axis),) * 3,
            out_specs=P(None, axis), check_vma=False)(q, k, v)


def test_transformer_config_rejects_unknown_attn_mode():
    """A typo'd mode must fail at config time — the dispatch would
    otherwise silently run full LOCAL attention per shard."""
    from horovod_tpu.models import TransformerConfig

    with pytest.raises(ValueError, match="unknown attn_mode"):
        TransformerConfig(attn_mode="zigzag")
