"""Estimator-lite contract tests (always run, pyspark stubbed): the
``fit(dataset) -> params`` bridge the reference covers with its Spark
estimators + Store (``spark/keras/estimator.py``,
``spark/common/store.py:1-582`` — role parity). Training, checkpoint
persistence, resume-from-latest, dataset materialization, and the
DataFrame front end all run in-process against the barrier stub from
test_spark.py."""

import sys
import types

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu.spark as hvd_spark
from horovod_tpu.spark import estimator as est

from test_spark import _StubBarrierContext, _StubSparkContext  # noqa: E402


@pytest.fixture()
def stub_pyspark(monkeypatch):
    import os
    sc = _StubSparkContext()
    mod = types.ModuleType("pyspark")
    mod.SparkContext = types.SimpleNamespace(_active_spark_context=sc)
    mod.BarrierTaskContext = _StubBarrierContext
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    before = dict(os.environ)
    yield sc
    for k in [k for k in os.environ if k.startswith("HVD_")
              and k not in before]:
        del os.environ[k]


def _make_regression(n=256, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    w_true = np.arange(1.0, d + 1.0, dtype=np.float32)[:, None]
    y = (x @ w_true)[:, 0] + 0.5
    return x, y.astype(np.float32)


def _init_fn(rng, batch):
    x, _ = batch
    return {"w": jnp.zeros((x.shape[1], 1), jnp.float32),
            "b": jnp.zeros((1,), jnp.float32)}


def _loss_fn(params, batch):
    x, y = batch
    pred = (x @ params["w"])[:, 0] + params["b"][0]
    return jnp.mean((pred - y) ** 2)


def _mse(params, x, y):
    pred = (x @ np.asarray(params["w"]))[:, 0] + np.asarray(params["b"])[0]
    return float(np.mean((pred - y) ** 2))


def test_fit_trains_from_arrays(stub_pyspark):
    import optax
    x, y = _make_regression()
    params = hvd_spark.fit((x, y), _init_fn, _loss_fn,
                           optimizer=optax.sgd(0.05), epochs=5,
                           batch_size=64, num_proc=1, seed=3)
    zero = {"w": np.zeros((x.shape[1], 1)), "b": np.zeros((1,))}
    assert _mse(params, x, y) < 0.1 * _mse(zero, x, y)
    assert isinstance(params["w"], np.ndarray)  # host-side result


def test_fit_checkpoints_and_resumes(stub_pyspark, tmp_path):
    import optax
    x, y = _make_regression(n=128)
    store = str(tmp_path / "store")
    out1 = est._fit_task((x, y), _init_fn, _loss_fn, optax.sgd(0.05),
                         2, 64, True, 0, store)
    assert out1["epochs_run"] == 2
    # rerun against the same Store: resumes past the latest checkpoint
    out2 = est._fit_task((x, y), _init_fn, _loss_fn, optax.sgd(0.05),
                         2, 64, True, 0, store)
    assert out2["epochs_run"] == 0
    np.testing.assert_allclose(out2["params"]["w"], out1["params"]["w"])
    # more epochs: trains only the remainder, starting from the checkpoint
    out3 = est._fit_task((x, y), _init_fn, _loss_fn, optax.sgd(0.05),
                         4, 64, True, 0, store)
    assert out3["epochs_run"] == 2
    assert _mse(out3["params"], x, y) <= _mse(out1["params"], x, y) + 1e-6


def test_save_dataset_roundtrip(tmp_path, stub_pyspark):
    import optax
    x, y = _make_regression(n=128)
    path = est.save_dataset(str(tmp_path / "store"), x, y)
    params = hvd_spark.fit(path, _init_fn, _loss_fn,
                           optimizer=optax.sgd(0.05), epochs=3,
                           batch_size=64, num_proc=1)
    zero = {"w": np.zeros((x.shape[1], 1)), "b": np.zeros((1,))}
    assert _mse(params, x, y) < _mse(zero, x, y)


class _StubDataFrame:
    """select(...).collect() -> rows supporting row[col] (pyspark.Row's
    mapping contract, enough for the driver-side materialization)."""

    def __init__(self, rows):
        self._rows = rows

    def select(self, *cols):
        return _StubDataFrame([{c: r[c] for c in cols} for r in self._rows])

    def collect(self):
        return self._rows


def test_fit_dataframe_materializes_then_trains(stub_pyspark, tmp_path):
    import optax
    x, y = _make_regression(n=96, d=2)
    rows = [{"f0": float(a), "f1": float(b), "label": float(t)}
            for (a, b), t in zip(x, y)]
    params = hvd_spark.fit_dataframe(
        _StubDataFrame(rows), ["f0", "f1"], ["label"], _init_fn, _loss_fn,
        store_path=str(tmp_path / "store"), optimizer=optax.sgd(0.05),
        epochs=4, batch_size=32, num_proc=1)
    zero = {"w": np.zeros((2, 1)), "b": np.zeros((1,))}
    assert _mse(params, x, y) < 0.5 * _mse(zero, x, y)
    # the dataset was materialized to the Store for the executors
    assert (tmp_path / "store" / "dataset.npz").exists()
