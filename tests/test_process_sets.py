"""Process-set tests (reference analog:
``test/parallel/test_process_sets_static.py`` /
``test_process_sets_dynamic`` paths in ``test_tensorflow.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_add_remove_process_set(hvd):
    ps = hvd.add_process_set([1, 3, 5])
    assert ps.process_set_id is not None and ps.process_set_id > 0
    assert ps.ranks == [1, 3, 5]
    assert ps.size() == 3
    assert ps.included(3) and not ps.included(2)
    assert ps.rank(5) == 2 and ps.rank(0) == -1
    hvd.remove_process_set(ps)
    assert ps.process_set_id is None


def test_duplicate_process_set_dedup(hvd):
    a = hvd.add_process_set([0, 2])
    b = hvd.add_process_set([2, 0])
    assert a.process_set_id == b.process_set_id
    hvd.remove_process_set(a)


def test_cannot_remove_global(hvd):
    with pytest.raises(ValueError):
        hvd.remove_process_set(hvd.global_process_set)


def test_allreduce_on_subset_eager(hvd):
    ps = hvd.add_process_set([0, 1, 2, 3])
    vals = [jnp.full((2,), i + 1.0) for i in range(4)]
    out = hvd.allreduce(hvd.per_rank(vals, ps), op=hvd.Sum, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), np.full((2,), 10.0))
    hvd.remove_process_set(ps)


def test_broadcast_on_subset_eager(hvd):
    ps = hvd.add_process_set([2, 5, 7])
    vals = [jnp.full((2,), r * 1.0) for r in [2, 5, 7]]
    out = hvd.broadcast(hvd.per_rank(vals, ps), 5, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), np.full((2,), 5.0))
    hvd.remove_process_set(ps)


def test_allgather_on_subset_eager(hvd):
    ps = hvd.add_process_set([1, 4])
    vals = [jnp.full((2, 2), r * 1.0) for r in [1, 4]]
    out = hvd.allgather(hvd.per_rank(vals, ps), process_set=ps)
    assert out.shape == (4, 2)
    np.testing.assert_allclose(np.asarray(out[:2]), 1.0)
    np.testing.assert_allclose(np.asarray(out[2:]), 4.0)
    hvd.remove_process_set(ps)


def test_subset_allreduce_traced(hvd):
    ps = hvd.add_process_set([0, 1, 2])
    x = jnp.arange(1.0, 9.0).reshape(8, 1)

    def step(v):
        return hvd.allreduce(v, op=hvd.Sum, process_set=ps)

    out = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    got = np.asarray(out).ravel()
    # members reduce to 1+2+3=6; non-members reduce within singleton groups
    np.testing.assert_allclose(got[:3], 6.0)
    np.testing.assert_allclose(got[3:], np.arange(4.0, 9.0))
    hvd.remove_process_set(ps)


def test_subset_allgather_traced(hvd):
    ps = hvd.add_process_set([1, 3, 5])
    x = jnp.arange(1.0, 9.0).reshape(8, 1)

    def step(v):
        return hvd.allgather(v, process_set=ps)

    out = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    got = np.asarray(out).reshape(8, 3)
    for row in (1, 3, 5):
        np.testing.assert_allclose(got[row], [2.0, 4.0, 6.0])
    hvd.remove_process_set(ps)


def test_subset_broadcast_traced(hvd):
    ps = hvd.add_process_set([2, 6])
    x = jnp.arange(1.0, 9.0).reshape(8, 1)

    def step(v):
        return hvd.broadcast(v, 6, process_set=ps)

    out = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    got = np.asarray(out).ravel()
    assert got[2] == 7.0 and got[6] == 7.0  # members got root's value
    np.testing.assert_allclose(got[[0, 1, 3, 4, 5, 7]],
                               [1.0, 2.0, 4.0, 5.0, 6.0, 8.0])
    hvd.remove_process_set(ps)


def test_dynamic_gate():
    import horovod_tpu.process_sets as psmod
    import horovod_tpu.runtime as rt
    table = rt.process_set_table()
    saved = table.dynamic_enabled
    table.dynamic_enabled = False
    try:
        with pytest.raises(RuntimeError):
            table.add([0, 1])
    finally:
        table.dynamic_enabled = saved


class TestSubsetTracedCollectives:
    """Ring-based subset alltoall/reducescatter/product inside traced code
    (previously NotImplementedError; the grouped lax primitives don't
    support unequal partitions)."""

    def _run(self, hvd, fn, data, out_spec=None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        import numpy as np
        mesh, axis = hvd.mesh(), hvd.axis_name()
        sharded = jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=P(axis),
            out_specs=out_spec if out_spec is not None else P(axis),
            check_vma=False))
        return np.asarray(sharded(jax.device_put(
            data, NamedSharding(mesh, P(axis)))))

    def test_subset_alltoall_traced(self, hvd):
        import jax.numpy as jnp
        import numpy as np
        n = hvd.size()
        if n < 4:
            import pytest
            pytest.skip("needs 4 devices")
        ps = hvd.add_process_set([0, 1, 2])
        try:
            k, chunk = 3, 2
            data = np.zeros((n, k * chunk, 2), np.float32)
            for r in range(k):
                for j in range(k):
                    data[r, j * chunk:(j + 1) * chunk] = r * 10 + j

            out = self._run(hvd, lambda x: hvd.alltoall(
                x[0], process_set=ps)[None], data)
            for r in range(k):  # member r receives chunk r of every member
                for j in range(k):
                    got = out[r, j * chunk:(j + 1) * chunk]
                    assert np.allclose(got, j * 10 + r), (r, j, got)
        finally:
            hvd.remove_process_set(ps)

    def test_subset_reducescatter_traced(self, hvd):
        import jax.numpy as jnp
        import numpy as np
        n = hvd.size()
        if n < 4:
            import pytest
            pytest.skip("needs 4 devices")
        ps = hvd.add_process_set([0, 2, 3])
        try:
            k, chunk = 3, 2
            data = np.zeros((n, k * chunk), np.float32)
            for i, r in enumerate([0, 2, 3]):
                data[r] = np.arange(k * chunk) + 100 * i

            out = self._run(hvd, lambda x: hvd.reducescatter(
                x[0], op=hvd.Sum, process_set=ps)[None], data)
            full_sum = data[[0, 2, 3]].sum(axis=0)
            for i, r in enumerate([0, 2, 3]):
                expect = full_sum[i * chunk:(i + 1) * chunk]
                assert np.allclose(out[r], expect), (r, out[r], expect)
        finally:
            hvd.remove_process_set(ps)

    def test_subset_product_traced(self, hvd):
        import numpy as np
        n = hvd.size()
        if n < 4:
            import pytest
            pytest.skip("needs 4 devices")
        ps = hvd.add_process_set([1, 2])
        try:
            data = np.ones((n, 3), np.float32)
            data[1] = [2, 3, 4]
            data[2] = [5, 6, 7]
            out = self._run(hvd, lambda x: hvd.allreduce(
                x[0], op=hvd.Product, process_set=ps)[None], data)
            assert np.allclose(out[1], [10, 18, 28])
            assert np.allclose(out[2], [10, 18, 28])
        finally:
            hvd.remove_process_set(ps)

    def test_subset_product_ring_odd_sizes(self, hvd):
        """Product ring with k=3 members and an element count not
        divisible by k (exercises chunk padding with the multiplicative
        identity) plus an int dtype for exactness."""
        import numpy as np
        n = hvd.size()
        if n < 4:
            import pytest
            pytest.skip("needs 4 devices")
        ps = hvd.add_process_set([0, 1, 3])
        try:
            data = np.ones((n, 5), np.int32)  # 5 % 3 != 0
            data[0] = [2, 1, 3, 1, 2]
            data[1] = [3, 2, 1, 5, 1]
            data[3] = [1, 4, 2, 1, 7]
            out = self._run(hvd, lambda x: hvd.allreduce(
                x[0], op=hvd.Product, process_set=ps)[None], data)
            expect = data[0] * data[1] * data[3]
            for r in (0, 1, 3):
                assert np.array_equal(out[r].astype(np.int64), expect), \
                    (r, out[r])
        finally:
            hvd.remove_process_set(ps)

    def test_subset_product_nonmember_keeps_value(self, hvd):
        import numpy as np
        n = hvd.size()
        if n < 4:
            import pytest
            pytest.skip("needs 4 devices")
        ps = hvd.add_process_set([1, 2])
        try:
            data = np.ones((n, 2), np.float32)
            data[0] = [9, 9]   # non-member: must come back unchanged
            data[1] = [2, 3]
            data[2] = [4, 5]
            out = self._run(hvd, lambda x: hvd.allreduce(
                x[0], op=hvd.Product, process_set=ps)[None], data)
            assert np.allclose(out[1], [8, 15])
            assert np.allclose(out[0], [9, 9]), out[0]
        finally:
            hvd.remove_process_set(ps)

    def test_subset_reducescatter_int_exact(self, hvd):
        """Native-dtype accumulation: int32 sums above 2^24 stay exact
        (code-review r3 regression — f32 accumulation rounded them)."""
        import numpy as np
        n = hvd.size()
        if n < 4:
            import pytest
            pytest.skip("needs 4 devices")
        ps = hvd.add_process_set([0, 1])
        try:
            big = 1 << 25
            data = np.zeros((n, 4), np.int32)
            data[0] = [big, 1, big, 1]
            data[1] = [1, big, 1, big]
            out = self._run(hvd, lambda x: hvd.reducescatter(
                x[0], op=hvd.Sum, process_set=ps)[None], data)
            assert out.dtype == np.int32
            assert list(out[0]) == [big + 1, big + 1]
            assert list(out[1]) == [big + 1, big + 1]
        finally:
            hvd.remove_process_set(ps)
