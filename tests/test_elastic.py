"""Elastic subsystem unit tests: state commit/restore/sync semantics,
discovery + blacklist, registry decisions, and the driver's round protocol
with mocked workers — "multi-node without a cluster" exactly like the
reference's ``test/single/test_elastic_driver.py`` (FixedHosts / scripted
discovery, no real processes)."""

import os
import threading
import time

import pytest

import horovod_tpu as hvd
from horovod_tpu.elastic import (
    ElasticDriver,
    ElasticRendezvous,
    FixedHosts,
    HostDiscoveryScript,
    HostManager,
    HostUpdateResult,
    HostsUpdatedInterrupt,
    JaxState,
    ObjectState,
    WorkerStateRegistry,
    run_fn,
)
from horovod_tpu.exceptions import HorovodInternalError
from horovod_tpu.runner.http_kv import KVServer


def _identity_bcast(obj):
    return obj


# --- State / ObjectState --------------------------------------------------

class TestObjectState:
    def test_save_restore(self):
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0, batch=5)
        state.epoch = 3
        state.batch = 7
        state.restore()
        assert state.epoch == 0 and state.batch == 5
        state.epoch = 3
        state.save()
        state.epoch = 9
        state.restore()
        assert state.epoch == 3

    def test_commit_added_requires_sync(self):
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0)
        state.on_hosts_updated(time.time(), HostUpdateResult.added)
        with pytest.raises(HostsUpdatedInterrupt) as exc:
            state.commit()
        assert not exc.value.skip_sync  # new workers must receive state

    def test_commit_removed_skips_sync(self):
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0)
        state.on_hosts_updated(time.time(), HostUpdateResult.removed)
        with pytest.raises(HostsUpdatedInterrupt) as exc:
            state.commit()
        assert exc.value.skip_sync  # survivors already consistent

    def test_commit_no_update_passes(self):
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0)
        state.commit()  # no notification: no interrupt

    def test_reset_callbacks(self):
        called = []
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0)
        state.register_reset_callbacks([lambda: called.append(1)])
        state.on_reset()
        assert called == [1]


class TestJaxState:
    def test_pytree_commit_restore(self):
        import jax.numpy as jnp
        import numpy as np
        params = {"w": jnp.ones((2, 2)), "b": jnp.zeros(2)}
        state = JaxState(params=params, epoch=0)
        state.params = {"w": jnp.full((2, 2), 5.0), "b": jnp.ones(2)}
        state.restore()
        np.testing.assert_allclose(np.asarray(state.params["w"]),
                                   np.ones((2, 2)))
        state.params = {"w": jnp.full((2, 2), 5.0), "b": jnp.ones(2)}
        state.save()
        state.params = {"w": jnp.zeros((2, 2)), "b": jnp.zeros(2)}
        state.restore()
        np.testing.assert_allclose(np.asarray(state.params["w"]),
                                   np.full((2, 2), 5.0))

    def test_sync_broadcasts(self):
        import numpy as np
        state = JaxState(params={"w": np.ones(3)}, epoch=4)
        state.sync()  # single-controller world: broadcast is identity
        assert state.epoch == 4


# --- run_fn recover loop --------------------------------------------------

class TestRunFn:
    def test_returns_result(self):
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0)
        wrapped = run_fn(lambda s: "done", reset=lambda: None)
        assert wrapped(state) == "done"

    def test_internal_error_restores_and_resets(self):
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0)
        resets = []
        calls = []

        def train(s):
            calls.append(1)
            if len(calls) == 1:
                s.epoch = 99  # uncommitted: must be rolled back
                raise HorovodInternalError("peer died")
            assert s.epoch == 0
            return "recovered"

        wrapped = run_fn(train, reset=lambda: resets.append(1))
        assert wrapped(state) == "recovered"
        assert resets == [1]

    def test_hosts_updated_syncs_and_resets(self):
        state = ObjectState(_identity_bcast, lambda: 0, epoch=0)
        seq = []

        def train(s):
            if not seq:
                seq.append("first")
                raise HostsUpdatedInterrupt(skip_sync=False)
            return "resumed"

        wrapped = run_fn(train, reset=lambda: seq.append("reset"))
        assert wrapped(state) == "resumed"
        assert seq == ["first", "reset"]


# --- discovery ------------------------------------------------------------

class TestHostManager:
    def test_added_and_removed(self):
        disc = FixedHosts({"a": 2})
        mgr = HostManager(disc)
        assert mgr.update_available_hosts() == HostUpdateResult.added
        assert mgr.current_hosts.count_available_slots() == 2

        disc.set({"a": 2, "b": 2})
        assert mgr.update_available_hosts() == HostUpdateResult.added
        assert mgr.current_hosts.host_assignment_order == ["a", "b"]

        disc.set({"b": 2})
        assert mgr.update_available_hosts() == HostUpdateResult.removed
        assert mgr.current_hosts.host_assignment_order == ["b"]

    def test_slot_growth_is_added(self):
        disc = FixedHosts({"a": 1})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        disc.set({"a": 4})
        assert mgr.update_available_hosts() == HostUpdateResult.added

    def test_no_change(self):
        disc = FixedHosts({"a": 2})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        assert mgr.update_available_hosts() == HostUpdateResult.no_update

    def test_blacklist_excludes_host(self):
        disc = FixedHosts({"a": 2, "b": 2})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        mgr.blacklist("a")
        assert mgr.is_blacklisted("a")
        assert mgr.current_hosts.host_assignment_order == ["b"]
        assert mgr.current_hosts.count_available_slots() == 2

    def test_order_preserves_oldest_first(self):
        order = HostManager.order_available_hosts({"c", "a", "b"}, ["b", "c"])
        assert order == ["b", "c", "a"]

    def test_cooldown_resurrection(self):
        disc = FixedHosts({"a": 2})
        mgr = HostManager(disc, cooldown_range=(1, 2))
        mgr.update_available_hosts()
        mgr.blacklist("a")
        assert mgr.current_hosts.count_available_slots() == 0
        time.sleep(2.5)  # cooldown (1s lower bound, doubling + jitter) ends
        res = mgr.update_available_hosts()
        assert res & HostUpdateResult.added
        assert not mgr.is_blacklisted("a")
        assert mgr.current_hosts.count_available_slots() == 2


class TestHostDiscoveryScript:
    def test_parses_output(self, tmp_path):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho host-1:4\necho host-2\n")
        script.chmod(0o755)
        disc = HostDiscoveryScript(str(script), default_slots=2)
        assert disc.find_available_hosts_and_slots() == \
            {"host-1": 4, "host-2": 2}

    def test_failure_raises(self, tmp_path):
        script = tmp_path / "bad.sh"
        script.write_text("#!/bin/sh\nexit 3\n")
        script.chmod(0o755)
        with pytest.raises(RuntimeError):
            HostDiscoveryScript(str(script)).find_available_hosts_and_slots()


# --- driver with mocked workers ------------------------------------------

class FakeProc:
    """Worker-process stand-in whose exit is scripted by the test."""

    def __init__(self):
        self._exit = threading.Event()
        self._code = None
        self.terminated = False

    def exit(self, code):
        self._code = code
        self._exit.set()

    def wait(self, timeout=None):
        self._exit.wait(timeout)
        return self._code

    def poll(self):
        return self._code if self._exit.is_set() else None

    def terminate(self):
        self.terminated = True
        if not self._exit.is_set():
            self.exit(143)


class DriverHarness:
    def __init__(self, host_slots, min_np, max_np=None, **kw):
        self.kv = KVServer()
        self.kv.start()
        self.discovery = FixedHosts(host_slots)
        self.rendezvous = ElasticRendezvous(self.kv)
        self.driver = ElasticDriver(self.rendezvous, self.discovery,
                                    min_np, max_np, timeout=10, **kw)
        self.procs = {}  # (host, slot) -> list of FakeProc (per spawn)
        self.lock = threading.Lock()

    def create_worker(self, slot_info, spec_round):
        proc = FakeProc()
        with self.lock:
            self.procs.setdefault(
                (slot_info.hostname, slot_info.local_rank), []).append(proc)
        return proc

    def start(self, np):
        self.driver.start(np, self.create_worker)

    def wait_for_workers(self, n, timeout=5):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                count = sum(len(v) for v in self.procs.values())
            if count >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(f"expected {n} spawned workers, got {count}")

    def stop(self):
        self.driver.stop()
        self.kv.stop()


class TestElasticDriver:
    def test_initial_spawn(self):
        h = DriverHarness({"a": 2, "b": 2}, min_np=2, max_np=4)
        try:
            h.start(2)
            h.wait_for_workers(4)  # elastic uses all slots up to max_np
            assert h.driver.world_size() == 4
            assert h.driver.has_rank_assignment("a", 0)
            assert h.driver.get_slot_info("a", 0).rank == 0
            spec_round = h.rendezvous.round_id
            assert spec_round == 1
            assert h.kv.get("elastic/round") == b"1"
        finally:
            h.stop()

    def test_worker_success_stops_job(self):
        h = DriverHarness({"a": 1}, min_np=1)
        try:
            h.start(1)
            h.wait_for_workers(1)
            h.procs[("a", 0)][0].exit(0)
            deadline = time.monotonic() + 5
            while not h.driver.finished() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert h.driver.finished()
            results = h.driver.get_results()
            assert results.worker_results["a[0]"][0] == 0
        finally:
            h.stop()

    def test_worker_failure_blacklists_and_resizes(self):
        h = DriverHarness({"a": 1, "b": 1}, min_np=1, max_np=2)
        try:
            h.start(2)
            h.wait_for_workers(2)
            h.procs[("b", 0)][0].exit(1)  # b dies
            deadline = time.monotonic() + 5
            while h.rendezvous.round_id < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            # host b blacklisted; new round published with only host a
            # (the registry clears per-round states when the round turns)
            assert h.rendezvous.round_id >= 2
            assert h.driver.world_size() == 1
            assert not h.driver.has_rank_assignment("b", 0)
            assert not h.driver.finished()
        finally:
            h.stop()

    def test_all_failures_stop_job(self):
        h = DriverHarness({"a": 1}, min_np=1)
        try:
            h.start(1)
            h.wait_for_workers(1)
            h.procs[("a", 0)][0].exit(1)
            deadline = time.monotonic() + 5
            while not h.driver.finished() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert h.driver.finished()
        finally:
            h.stop()

    def test_host_added_triggers_new_round(self):
        h = DriverHarness({"a": 1}, min_np=1, max_np=4)
        try:
            h.start(1)
            h.wait_for_workers(1)
            h.discovery.set({"a": 1, "b": 1})
            deadline = time.monotonic() + 8
            while time.monotonic() < deadline:
                if h.rendezvous.round_id >= 2 and ("b", 0) in h.procs:
                    break
                time.sleep(0.05)
            assert h.rendezvous.round_id >= 2
            assert ("b", 0) in h.procs  # new worker spawned on b
            assert h.driver.world_size() == 2
            # notify key written for existing workers
            assert h.kv.get("elastic/notify") is not None
        finally:
            h.stop()

    def test_slot_lost_exit_is_ignored(self):
        h = DriverHarness({"a": 1, "b": 1}, min_np=1, max_np=2)
        try:
            h.start(2)
            h.wait_for_workers(2)
            h.discovery.set({"a": 1})  # b removed by discovery
            deadline = time.monotonic() + 8
            while h.rendezvous.round_id < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            from horovod_tpu.elastic.driver import SLOT_LOST_EXIT_CODE
            h.procs[("b", 0)][0].exit(SLOT_LOST_EXIT_CODE)
            time.sleep(0.3)
            assert not h.driver.finished()
            assert h.driver.registry.count("FAILURE") == 0
        finally:
            h.stop()

    def test_scale_down_then_replace_recovers(self):
        """Discovery must keep polling while a resume() holds the round lock
        parked in wait_for_available_slots (slots < min_np). Regression for
        the scale-down-then-replace freeze: blacklisting drops the world
        below min_np, then a *replacement* host appears and must still be
        discovered so the waiting round can proceed."""
        h = DriverHarness({"a": 1, "b": 1}, min_np=2, max_np=2)
        try:
            h.start(2)
            h.wait_for_workers(2)
            h.procs[("b", 0)][0].exit(1)  # b dies -> blacklist -> 1 slot < min_np
            time.sleep(1.5)  # resume() is now parked holding _round_lock
            assert not h.driver.finished()
            h.discovery.set({"a": 1, "c": 1})  # replacement host appears
            deadline = time.monotonic() + 8
            while time.monotonic() < deadline:
                if h.rendezvous.round_id >= 2 and ("c", 0) in h.procs:
                    break
                time.sleep(0.05)
            assert h.rendezvous.round_id >= 2
            assert ("c", 0) in h.procs, "replacement host was never activated"
            assert h.driver.world_size() == 2
            assert not h.driver.finished()
        finally:
            h.stop()

    def test_discovery_defers_update_when_round_lock_held(self):
        h = DriverHarness({"a": 1}, min_np=1, max_np=2)
        try:
            h.start(1)
            h.wait_for_workers(1)
            from horovod_tpu.elastic.state import HostUpdateResult
            assert h.driver._round_lock.acquire(timeout=5)
            try:
                # _round_lock is reentrant, so the contended call must come
                # from another thread (as it does from the discovery thread).
                t = threading.Thread(
                    target=h.driver._on_hosts_updated,
                    args=(HostUpdateResult.added,))
                t0 = time.monotonic()
                t.start()
                t.join(timeout=2.0)
                assert not t.is_alive(), "_on_hosts_updated blocked on lock"
                assert time.monotonic() - t0 < 2.0
                assert h.driver._deferred_update == HostUpdateResult.added
            finally:
                h.driver._round_lock.release()
        finally:
            h.stop()

    def test_reset_limit_stops_job(self):
        h = DriverHarness({"a": 1, "b": 1, "c": 1}, min_np=1, max_np=3,
                          reset_limit=1)
        try:
            h.start(3)
            h.wait_for_workers(3)
            h.procs[("c", 0)][0].exit(1)  # reset 1: allowed
            deadline = time.monotonic() + 5
            while h.rendezvous.round_id < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            h.procs[("b", 0)][0].exit(1)  # reset 2: over the limit
            deadline = time.monotonic() + 5
            while not h.driver.finished() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert h.driver.finished()
            assert "reset limit" in (h.driver.get_results().error_message or "")
        finally:
            h.stop()


class TestWorkerStateRegistry:
    class _StubDriver:
        def __init__(self):
            self.stopped = False
            self.resumed = 0

        def finished(self):
            return self.stopped

        def stop(self, error_message=None, success=False):
            self.stopped = True
            self.error = error_message
            self.success = success

        def resume(self):
            self.resumed += 1

    def test_ready_records(self):
        drv = self._StubDriver()
        mgr = HostManager(FixedHosts({"a": 2}))
        reg = WorkerStateRegistry(drv, mgr)
        reg.reset(2)
        reg.record_ready("a", 0)
        reg.record_ready("a", 1)
        assert reg.count("READY") == 2
        assert not drv.stopped

    def test_success_stops(self):
        drv = self._StubDriver()
        reg = WorkerStateRegistry(drv, HostManager(FixedHosts({"a": 1})))
        reg.reset(1)
        reg.record_success("a", 0)
        assert drv.stopped

    def test_failure_blacklists_and_resumes(self):
        drv = self._StubDriver()
        mgr = HostManager(FixedHosts({"a": 1, "b": 1}))
        mgr.update_available_hosts()
        reg = WorkerStateRegistry(drv, mgr)
        reg.reset(2)
        reg.record_ready("a", 0)
        reg.record_failure("b", 0)
        assert mgr.is_blacklisted("b")
        assert drv.resumed == 1
        assert not drv.stopped
