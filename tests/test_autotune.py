"""Autotuner tests (reference: ``parameter_manager.cc`` discipline — warmup
discard, per-sample scoring, env-fixed knobs untunable, CSV log)."""

import csv
import os

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import autotune
from horovod_tpu.autotune import ParameterManager, Tunable
from horovod_tpu.utils import envs


@pytest.fixture(autouse=True)
def _clean_overrides():
    yield
    envs.clear_overrides()
    autotune.reset()


def make_manager(score_of, tunables, **kw):
    """Manager driven by a deterministic score function: instead of wall
    time, each sample is scored by score_of(config dict)."""
    mgr = ParameterManager(tunables=tunables, warmup_samples=0,
                           steps_per_sample=1, **kw)

    def run_until_converged(max_iter=200):
        it = 0
        while not mgr.converged and it < max_iter:
            mgr._end_sample(score_of(mgr.current_config()))
            it += 1
        return it

    return mgr, run_until_converged


def test_coordinate_search_finds_best_config():
    tun = [Tunable("A", [1, 2, 4, 8]), Tunable("B", [0, 1])]

    # peak at A=4, B=1
    def score(cfg):
        return 100 - abs(cfg["A"] - 4) * 10 + cfg["B"] * 5

    mgr, run = make_manager(score, tun)
    run()
    assert mgr.converged
    assert mgr.current_config() == {"A": 4, "B": 1}
    # overrides applied so knob readers see the tuned values
    assert envs.get("A") == "4"
    assert envs.get("B") == "1"


def test_env_fixed_knob_excluded(monkeypatch):
    monkeypatch.setenv("HVD_A", "2")
    tun = [Tunable("A", [1, 2, 4, 8]), Tunable("B", [0, 1])]
    assert tun[0].fixed

    def score(cfg):
        return cfg["B"] * 10 + cfg["A"]

    mgr, run = make_manager(score, tun)
    run()
    assert mgr.converged
    # A was never moved; env value wins over any override
    assert envs.get("A") == "2"
    assert mgr.current_config()["B"] == 1


def test_all_fixed_means_converged(monkeypatch):
    monkeypatch.setenv("HVD_A", "1")
    mgr = ParameterManager(tunables=[Tunable("A", [1, 2])])
    assert mgr.converged


def test_max_samples_bounds_search():
    tun = [Tunable("A", list(range(10)))]
    calls = []

    def score(cfg):
        calls.append(cfg["A"])
        return float(cfg["A"])  # keeps improving: would never self-converge

    mgr, run = make_manager(score, tun, max_samples=5)
    run()
    assert mgr.converged
    assert len(calls) <= 6


def test_log_csv_written(tmp_path):
    log = tmp_path / "autotune.csv"
    tun = [Tunable("A", [1, 2])]
    mgr, run = make_manager(lambda cfg: float(cfg["A"]), tun,
                            log_path=str(log))
    run()
    rows = list(csv.reader(open(log)))
    assert rows[0] == ["sample", "score_bytes_per_sec", "warmup", "converged", "A"]
    assert len(rows) > 2


def test_warmup_samples_discarded():
    tun = [Tunable("A", [1, 2])]
    mgr = ParameterManager(tunables=tun, warmup_samples=2,
                           steps_per_sample=1)
    # huge warmup scores must not bias the search
    mgr._end_sample(1e12)
    mgr._end_sample(1e12)
    assert mgr._best_score is None
    mgr._end_sample(5.0)
    assert mgr._best_score == 5.0


def test_record_sample_boundary():
    tun = [Tunable("A", [1, 2])]
    mgr = ParameterManager(tunables=tun, warmup_samples=0,
                           steps_per_sample=3)
    mgr.record(100)
    mgr.record(100)
    assert mgr._sample_idx == 0
    mgr.record(100)  # third record closes the sample
    assert mgr._sample_idx == 1


def test_process_manager_gated_by_env(monkeypatch):
    autotune.reset()
    monkeypatch.delenv("HVD_AUTOTUNE", raising=False)
    monkeypatch.delenv("HOROVOD_AUTOTUNE", raising=False)
    assert autotune.get_manager() is None
    autotune.reset()
    monkeypatch.setenv("HVD_AUTOTUNE", "1")
    monkeypatch.setenv("HVD_AUTOTUNE_LOG", "")
    mgr = autotune.get_manager()
    assert mgr is not None
    # record flows through the module hook
    for _ in range(mgr.steps_per_sample):
        autotune.record(1024)
    assert mgr._sample_idx == 1


def test_eager_allreduce_records_bytes(monkeypatch):
    autotune.reset()
    monkeypatch.setenv("HVD_AUTOTUNE", "1")
    mgr = autotune.get_manager()
    before = (mgr._sample_idx, mgr._steps)
    x = hvd.per_rank([jnp.ones((4,)) * i for i in range(hvd.size())])
    hvd.allreduce(x, op=hvd.ReduceOp.AVERAGE, name="autotune_probe")
    after = (mgr._sample_idx, mgr._steps)
    assert after != before


def test_fusion_bucketing_numerics():
    """Tiny threshold forces many buckets; results must match unfused."""
    n = hvd.size()
    tensors = [hvd.per_rank([jnp.full((7,), float(r * 10 + i))
                             for r in range(n)]) for i in range(5)]
    expect = [np.mean([r * 10 + i for r in range(n)]) for i in range(5)]
    envs.set_override(envs.FUSION_THRESHOLD, 8)  # 8 bytes: 1 tensor/bucket
    try:
        out = hvd.grouped_allreduce(tensors, op=hvd.ReduceOp.AVERAGE)
    finally:
        envs.clear_override(envs.FUSION_THRESHOLD)
    for o, e in zip(out, expect):
        assert np.allclose(np.asarray(o), e)
    out2 = hvd.grouped_allreduce(tensors, op=hvd.ReduceOp.AVERAGE)
    for o, e in zip(out2, expect):
        assert np.allclose(np.asarray(o), e)


def test_fuse_by_dtype_respects_threshold():
    from horovod_tpu.ops.collectives import _fuse_by_dtype
    n = 4
    bundles = [jnp.zeros((n, 100), jnp.float32) for _ in range(4)]  # 400 B each
    envs.set_override(envs.FUSION_THRESHOLD, 500)
    try:
        fused, metas = _fuse_by_dtype(bundles, n)
    finally:
        envs.clear_override(envs.FUSION_THRESHOLD)
    assert len(fused) == 4  # 400+400 > 500 -> one tensor per bucket
    envs.set_override(envs.FUSION_THRESHOLD, 1000)
    try:
        fused2, _ = _fuse_by_dtype(bundles, n)
    finally:
        envs.clear_override(envs.FUSION_THRESHOLD)
    assert len(fused2) == 2  # two per bucket


def test_kv_score_sync_protocol():
    """Rank 0 decides from the mean score; followers read the decision."""
    from horovod_tpu.autotune import KVScoreSync

    class FakeKV(dict):
        def put(self, k, v):
            self[k] = v

        def wait(self, k, timeout=0):
            return self[k]

    kv = FakeKV()
    s0 = KVScoreSync(kv, 2, 0)
    s1 = KVScoreSync(kv, 2, 1)
    seen = {}

    def decide(mean_score):
        seen["score"] = mean_score
        return {"state": [1], "converged": False}

    kv.put("autotune/score/0/1", b"3.0")  # rank 1 reports first
    out0 = s0(0, 1.0, decide)
    assert seen["score"] == pytest.approx(2.0)
    out1 = s1(0, 3.0, lambda s: pytest.fail("follower must not decide"))
    assert out0 == out1 == {"state": [1], "converged": False}


# -- Bayesian strategy (reference optim/bayesian_optimization.cc parity) ----


def test_gp_regressor_interpolates_with_uncertainty():
    from horovod_tpu.optim.bayes import GaussianProcessRegressor

    X = np.array([[0.0], [0.25], [0.5], [0.75], [1.0]])
    y = np.sin(X[:, 0] * np.pi)
    gp = GaussianProcessRegressor(alpha=1e-6)
    gp.fit(X, y)
    mu, sd = gp.predict(X)
    assert np.allclose(mu, y, atol=1e-2)      # near-interpolation
    assert np.all(sd < 0.1)                    # low uncertainty at data
    _, sd_far = gp.predict(np.array([[2.5]]))
    assert sd_far[0] > sd.max()                # high uncertainty off-data


def test_bayesian_optimization_finds_peak():
    from horovod_tpu.optim.bayes import BayesianOptimization

    def f(x):  # peak at 0.3
        return -((x - 0.3) ** 2)

    bo = BayesianOptimization([(0.0, 1.0)], alpha=1e-4, seed=1)
    x = 0.9
    for _ in range(12):
        bo.add_sample([x], f(x))
        x = float(bo.next_sample()[0][0])
    best = bo._X[int(np.argmax(bo._y))][0]
    assert abs(best - 0.3) < 0.12, best


def test_bayesian_strategy_finds_best_config(monkeypatch):
    monkeypatch.setenv("HVD_AUTOTUNE_STRATEGY", "bayesian")
    tun = [Tunable("A", [1, 2, 4, 8]), Tunable("B", [0, 1])]

    def score(cfg):  # peak at A=4, B=1
        return 100 - abs(cfg["A"] - 4) * 10 + cfg["B"] * 5

    mgr, run = make_manager(score, tun)
    assert mgr.strategy == "bayesian"
    run()
    assert mgr.converged
    assert mgr.current_config() == {"A": 4, "B": 1}


def test_bayesian_strategy_respects_sample_budget(monkeypatch):
    monkeypatch.setenv("HVD_AUTOTUNE_STRATEGY", "bayesian")
    tun = [Tunable("A", list(range(8)))]
    calls = []

    def score(cfg):
        calls.append(cfg["A"])
        return float(cfg["A"])  # monotone: EI stays interesting

    mgr, run = make_manager(score, tun, max_samples=10)
    run()
    assert mgr.converged
    assert len(calls) <= 12  # budget + the convergence sample
    assert mgr.current_config()["A"] == max(calls)
