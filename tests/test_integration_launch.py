"""End-to-end static launch integration test: real hvdrun spawning real
worker processes that rendezvous through jax.distributed on CPU — the
analog of the reference's ``test/integration/test_static_run.py`` (full
horovodrun on localhost)."""

import subprocess
import sys
import textwrap
from backend_markers import skip_if_cpu_backend

pytestmark = skip_if_cpu_backend


WORKER = textwrap.dedent("""\
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    import jax.numpy as jnp
    hvd.init()
    out = hvd.allreduce(jnp.ones(4) * (hvd.rank() + 1), op=hvd.Sum)
    gathered = hvd.allgather(jnp.array([float(hvd.rank())]))
    print("RESULT", hvd.rank(), hvd.size(), float(out[0]), gathered.tolist(),
          flush=True)
""")


def test_static_run_two_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2", "--",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=300,
        env={k: v for k, v in __import__("os").environ.items()
             if k != "XLA_FLAGS"})
    assert proc.returncode == 0, proc.stderr
    lines = sorted(l for l in proc.stdout.splitlines() if "RESULT" in l)
    assert len(lines) == 2
    # 2 processes x 2 chips: world size 4; representative ranks 0 and 2.
    # p0 chips contribute 1.0 each, p1 chips contribute 3.0 each -> sum 8.
    assert "RESULT 0 4 8.0" in lines[0]
    assert "RESULT 2 4 8.0" in lines[1]
