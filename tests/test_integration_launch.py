"""End-to-end static launch integration test: real hvdrun spawning real
worker processes that rendezvous through jax.distributed on CPU — the
analog of the reference's ``test/integration/test_static_run.py`` (full
horovodrun on localhost).

The spawn variant stays marked for real-hardware runs
(``skip_if_cpu_backend``); ``hvdrun --loopback`` runs the same worker
contract as rank THREADS in one interpreter (docs/loopback.md) and is
exercised unconditionally below."""

import os
import subprocess
import sys
import textwrap

from backend_markers import skip_if_cpu_backend


WORKER = textwrap.dedent("""\
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    import jax.numpy as jnp
    hvd.init()
    out = hvd.allreduce(jnp.ones(4) * (hvd.rank() + 1), op=hvd.Sum)
    gathered = hvd.allgather(jnp.array([float(hvd.rank())]))
    print("RESULT", hvd.rank(), hvd.size(), float(out[0]), gathered.tolist(),
          flush=True)
""")


@skip_if_cpu_backend
def test_static_run_two_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2", "--",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=300,
        env={k: v for k, v in os.environ.items() if k != "XLA_FLAGS"})
    assert proc.returncode == 0, proc.stderr
    lines = sorted(l for l in proc.stdout.splitlines() if "RESULT" in l)
    assert len(lines) == 2
    # 2 processes x 2 chips: world size 4; representative ranks 0 and 2.
    # p0 chips contribute 1.0 each, p1 chips contribute 3.0 each -> sum 8.
    assert "RESULT 0 4 8.0" in lines[0]
    assert "RESULT 2 4 8.0" in lines[1]


LOOPBACK_WORKER = textwrap.dedent("""\
    import sys
    import horovod_tpu as hvd
    import jax.numpy as jnp
    hvd.init()
    out = hvd.allreduce(jnp.ones(4) * (hvd.rank() + 1), op=hvd.Sum)
    gathered = hvd.allgather(jnp.array([float(hvd.rank())]))
    # rank threads share stdout (docs/loopback.md fidelity limits):
    # one write per line, or prints interleave
    sys.stdout.write("RESULT %d %d %s %s\\n" % (
        hvd.rank(), hvd.size(), float(out[0]), gathered.tolist()))
    sys.stdout.flush()
""")


def test_static_run_two_ranks_loopback(tmp_path):
    """The loopback port of the static launch test: one interpreter, two
    rank threads, real negotiation over the in-process KV — works on the
    jax<0.5 CPU backend where the spawn variant must skip."""
    script = tmp_path / "worker.py"
    script.write_text(LOOPBACK_WORKER)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "--loopback",
         "-np", "2", "--", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300,
        env={k: v for k, v in os.environ.items() if k != "XLA_FLAGS"})
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\n{proc.stderr}"
    lines = sorted(l for l in proc.stdout.splitlines() if "RESULT" in l)
    assert len(lines) == 2, proc.stdout
    # 2 rank threads, 1 chip each: world size 2; 1.0 + 2.0 -> 3.0
    assert "RESULT 0 2 3.0" in lines[0]
    assert "RESULT 1 2 3.0" in lines[1]
