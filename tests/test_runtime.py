"""Runtime init / rank-query tests (reference analog:
``test/parallel/test_tensorflow.py`` rank/size tests and
``horovod/common/basics.py`` behavior)."""

import pytest


def test_initialized(hvd):
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.local_size() == 8  # single process drives all virtual chips
    assert hvd.rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.process_count() == 1
    assert hvd.is_homogeneous()


def test_mesh_shape(hvd):
    mesh = hvd.mesh()
    assert mesh.shape[hvd.axis_name()] == 8
    assert len(hvd.devices()) == 8


def test_double_init_is_noop(hvd):
    hvd.init()  # second call must not raise or reset state
    assert hvd.size() == 8


def test_uninitialized_raises():
    import horovod_tpu.runtime as rt
    saved = rt._state
    rt._state = None
    try:
        with pytest.raises(rt.NotInitializedError):
            rt.size()
    finally:
        rt._state = saved


def test_global_process_set(hvd):
    ps = hvd.global_process_set
    assert ps.process_set_id == 0
    assert ps.size() == 8
    assert ps.ranks == list(range(8))
    assert ps.included(3)
    assert ps.rank(5) == 5


def test_capability_queries():
    """Reference basics.py:273-371 migration shims: feature probes run
    unmodified; the single backend is XLA."""
    import horovod_tpu as hvd
    assert hvd.xla_built() and hvd.xla_enabled()
    assert hvd.mpi_threads_supported()
    assert not hvd.mpi_enabled() and not hvd.mpi_built()
    assert not hvd.gloo_enabled() and not hvd.gloo_built()
    assert not hvd.nccl_built() and not hvd.ddl_built()
    assert not hvd.ccl_built() and not hvd.cuda_built()
    assert not hvd.rocm_built()
    assert hvd.tpu_built() in (True, False)  # backend-dependent


def test_cluster_world_hint_requires_per_task_rank_var(monkeypatch):
    """`#SBATCH --ntasks=8` + plain `python` exports SLURM_NTASKS but no
    SLURM_PROCID — init must NOT attempt a blocking multi-process join
    (code-review r4)."""
    from horovod_tpu import runtime as rt
    for wv, rv in rt._CLUSTER_ENV_PAIRS:
        monkeypatch.delenv(wv, raising=False)
        monkeypatch.delenv(rv, raising=False)
    assert rt._cluster_world_hint() == 1
    monkeypatch.setenv("SLURM_NTASKS", "8")
    assert rt._cluster_world_hint() == 1  # no SLURM_PROCID: batch script
    monkeypatch.setenv("SLURM_PROCID", "3")
    assert rt._cluster_world_hint() == 8  # inside an srun task
    monkeypatch.setenv("SLURM_NTASKS", "garbage")
    assert rt._cluster_world_hint() == 1
