"""Loopback multi-rank world: the full world>1 stack on the CPU backend.

These tests are the tier-1 replacement for the 16 spawn-based
integration tests that skip on jax<0.5's CPU backend ("Multiprocess
computations aren't implemented on the CPU backend"): the negotiation
protocol, joined-rank reconstruction, watchdog fast-abort, elastic
re-forming, and step-capture ``negotiate_step`` replay all run at
world>=4 inside ONE interpreter (docs/loopback.md). The spawn variants
in test_integration_* stay marked for real-hardware runs.
"""

import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from backend_markers import loopback_world  # noqa: F401  (fixture)
from horovod_tpu import _native
from horovod_tpu.dynamic import HorovodCollectiveError
from horovod_tpu.exceptions import PeerFailureError
from horovod_tpu.loopback.context import RankKilled
from horovod_tpu.utils import faults as _faults

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")


FAST_HEALTH = {"HVD_HEALTH_INTERVAL": "0.3", "HVD_HEALTH_TIMEOUT": "1.5"}


def _results(outs):
    return [o.result for o in outs]


class TestNegotiatedCollectives:
    def test_matching_metadata_succeeds(self, loopback_world):
        n = loopback_world.size

        def body():
            out = hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="grads")
            assert out.shape == (4,)
            assert np.allclose(np.asarray(out), n)
            out2 = hvd.allreduce(jnp.ones(3), op=hvd.Sum)  # auto-named
            assert np.allclose(np.asarray(out2), n)
            return "OK"

        assert _results(loopback_world.run(body)) == ["OK"] * n

    def test_shape_mismatch_raises_informative_error(self, loopback_world):
        def body():
            shape = 4 if hvd.rank() == 0 else 5
            try:
                hvd.allreduce(jnp.ones(shape), op=hvd.Sum, name="bad")
                return "NO_ERROR"
            except HorovodCollectiveError as e:
                assert "Mismatched ALLREDUCE tensor shapes" in str(e), str(e)
                assert "[4]" in str(e) and "[5]" in str(e), str(e)
                return "GOT_MISMATCH_ERROR"

        outs = _results(loopback_world.run(body))
        assert outs == ["GOT_MISMATCH_ERROR"] * loopback_world.size

    def test_op_mismatch_raises(self, loopback_world):
        def body():
            try:
                if hvd.rank() == 0:
                    hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="op_clash")
                else:
                    hvd.allgather(jnp.ones(4), name="op_clash")
                return "NO_ERROR"
            except HorovodCollectiveError as e:
                assert "Mismatched collective operations" in str(e), str(e)
                return "GOT_OP_ERROR"

        outs = _results(loopback_world.run(body))
        assert outs == ["GOT_OP_ERROR"] * loopback_world.size

    def test_engine_disabled_by_knob(self):
        with hvd.loopback.world(
                2, extra_env={"HVD_DYNAMIC_ENGINE": "0"}) as w:
            def body():
                from horovod_tpu import engine_service
                assert engine_service.get_service() is None
                return "OK"

            assert _results(w.run(body)) == ["OK", "OK"]

    def test_grouped_and_broadcast(self, loopback_world):
        n = loopback_world.size

        def body():
            r = hvd.rank()
            outs = hvd.grouped_allreduce(
                [jnp.full((3,), float(r)), jnp.ones(2)], op=hvd.Sum,
                name="grp")
            assert np.allclose(np.asarray(outs[0]), sum(range(n)))
            assert np.allclose(np.asarray(outs[1]), float(n))
            b = hvd.broadcast(jnp.full((3,), float(r)), root_rank=1,
                              name="bc")
            assert np.allclose(np.asarray(b), 1.0), b
            return "OK"

        assert _results(loopback_world.run(body)) == ["OK"] * n


class TestPerProcessSetNegotiation:
    """Subset eager ops negotiate among member processes only, at a real
    world>1 (the loopback port of the 2-of-3 spawn test)."""

    def test_subset_collectives_without_nonmember(self):
        with hvd.loopback.world(
                3, extra_env={"HVD_DYNAMIC_PROCESS_SETS": "1"}) as w:
            def body():
                rank = hvd.rank()
                ps = hvd.add_process_set([0, 1])
                if rank < 2:
                    x = hvd.per_rank(
                        [jnp.full((4,), float(q + 1)) for q in (0, 1)],
                        process_set=ps)
                    out = hvd.allreduce(x, op=hvd.Sum, process_set=ps,
                                        name="sub")
                    assert np.allclose(np.asarray(out), 3.0), out
                    out2 = hvd.allreduce(x, op=hvd.Sum, process_set=ps)
                    g = hvd.allgather(hvd.per_rank(
                        [jnp.full((1,), float(q)) for q in (0, 1)],
                        process_set=ps), process_set=ps)
                    assert np.allclose(np.asarray(g), [0.0, 1.0]), g
                # all three: auto-name counters must still agree
                out3 = hvd.allreduce(jnp.ones(3), op=hvd.Sum)
                assert np.allclose(np.asarray(out3), 3.0), out3
                return "OK"

            assert _results(w.run(body)) == ["OK"] * 3

    def test_subset_mismatch_detected_among_members(self):
        with hvd.loopback.world(
                3, extra_env={"HVD_DYNAMIC_PROCESS_SETS": "1"}) as w:
            def body():
                rank = hvd.rank()
                ps = hvd.add_process_set([0, 1])
                got = "WORKER_OK"
                if rank < 2:
                    shape = 4 if rank == 0 else 5
                    x = hvd.per_rank([jnp.ones(shape) for _ in (0, 1)],
                                     process_set=ps)
                    try:
                        hvd.allreduce(x, op=hvd.Sum, process_set=ps,
                                      name="clash")
                        got = "NO_ERROR"
                    except HorovodCollectiveError as e:
                        assert "Mismatched ALLREDUCE tensor shapes" \
                            in str(e), str(e)
                        got = "GOT_MISMATCH"
                return got

            outs = _results(w.run(body))
            assert outs[:2] == ["GOT_MISMATCH", "GOT_MISMATCH"], outs
            assert outs[2] == "WORKER_OK"


class TestRaggedAllgather:
    def test_local_tensors_with_different_first_dims(self):
        with hvd.loopback.world(2) as w:
            def body():
                rank = hvd.rank()
                d0 = 2 if rank == 0 else 5
                out = hvd.allgather(jnp.full((d0, 3), float(rank + 1)),
                                    name="rag")
                assert out.shape == (7, 3), out.shape
                assert np.allclose(np.asarray(out[:2]), 1.0), out
                assert np.allclose(np.asarray(out[2:]), 2.0), out
                d0b = 4 if rank == 0 else 1
                out2 = hvd.allgather(jnp.full((d0b, 3), float(rank + 1)),
                                     name="rag2")
                assert out2.shape == (5, 3), out2.shape
                return "OK"

            assert _results(w.run(body)) == ["OK", "OK"]

    def test_allgather_sizes_not_cache_stale(self):
        with hvd.loopback.world(2) as w:
            def body():
                rank = hvd.rank()
                for step, peer_d0 in enumerate((3, 6)):
                    d0 = 2 if rank == 0 else peer_d0
                    out = hvd.allgather(jnp.full((d0, 2), float(rank)),
                                        name=f"s{step}")
                    assert out.shape == (2 + peer_d0, 2), (step, out.shape)
                return "OK"

            assert _results(w.run(body)) == ["OK", "OK"]


class TestJoin:
    def test_uneven_steps_with_join(self):
        with hvd.loopback.world(2) as w:
            def body():
                rank = hvd.rank()
                if rank == 0:
                    for step in range(2):
                        out = hvd.allreduce(jnp.full((3,), 6.0),
                                            op=hvd.Average, name=f"g{step}")
                        # joined rank contributes zeros; average over world
                        assert np.allclose(np.asarray(out), 3.0), (step, out)
                return hvd.join()

            outs = _results(w.run(body))
            assert len(set(outs)) == 1, outs  # same last-joined rank

    def test_join_with_grouped_and_barrier(self):
        with hvd.loopback.world(2) as w:
            def body():
                if hvd.rank() == 0:
                    xs = [jnp.full((2,), float(i + 1)) for i in range(3)]
                    outs = hvd.grouped_allreduce(xs, op=hvd.Sum, name="grp")
                    for i, o in enumerate(outs):
                        assert np.allclose(np.asarray(o), i + 1.0), (i, o)
                    hvd.barrier()
                    hvd.join()
                else:
                    hvd.join()
                return "OK"

            assert _results(w.run(body)) == ["OK", "OK"]

    def test_allgather_while_joined(self):
        with hvd.loopback.world(2) as w:
            def body():
                if hvd.rank() == 0:
                    out = hvd.allgather(jnp.full((3, 2), 7.0), name="g1")
                    assert out.shape == (3, 2), out.shape  # peer: 0 rows
                    assert np.allclose(np.asarray(out), 7.0), out
                    out2 = hvd.allgather(jnp.full((5,), 2.0), name="g2")
                    assert out2.shape == (5,), out2.shape
                    out3 = hvd.allgather(jnp.zeros((0, 3)), name="g3")
                    assert out3.shape == (0, 3), out3.shape
                    hvd.join()
                else:
                    hvd.join()
                return "OK"

            assert _results(w.run(body)) == ["OK", "OK"]

    def test_scalar_allgather_while_joined(self):
        """A SCALAR gather while the peer is joined: the joined rank
        must pair with the active rank's exchange contributing a zero
        scalar (the real path runs an (n, 1) program with zeros) —
        this deadlocked before the code-review fix."""
        with hvd.loopback.world(2) as w:
            def body():
                if hvd.rank() == 0:
                    out = hvd.allgather(jnp.float32(3.0), name="sg")
                    assert out.shape == (2,), out.shape
                    assert np.allclose(np.asarray(out), [3.0, 0.0]), out
                    hvd.join()
                else:
                    hvd.join()
                return "OK"

            assert _results(w.run(body, timeout=120)) == ["OK", "OK"]


class TestLoopbackEnvContract:
    """The loopback analog of the KV-bootstrap spawn test: the world
    seeds the full launcher contract; a half-configured environment must
    fail fast with a clear message instead of hanging on KV connect
    (ISSUE-10 satellite fix)."""

    def test_half_configured_overlay_rejected(self):
        with hvd.loopback.world(2) as w:
            def body():
                hvd.shutdown()
                from horovod_tpu.loopback import context as lbctx
                ctx = lbctx.current()
                ctx.env.pop("HVD_KV_ADDR", None)
                try:
                    hvd.init()
                    return "NO_ERROR"
                except RuntimeError as e:
                    assert "half-configured" in str(e), str(e)
                    return "REJECTED"

            outs = w.run(body, allow_failures=True)
            assert [o.result for o in outs] == ["REJECTED", "REJECTED"]

    def test_loopback_marker_without_context_rejected(self, monkeypatch):
        monkeypatch.setenv("HVD_LOOPBACK", "1")
        from horovod_tpu import runtime as rt
        # the session world is initialized; call the guarded branch
        # directly on a fresh-state probe: init() must raise before
        # touching any KV machinery
        with pytest.raises(RuntimeError, match="loopback rank context"):
            # session runtime is already initialized, so force the check
            # by calling init() — the loopback guard fires before the
            # "called twice" fast path
            rt.init()


class TestNumericsParity:
    """Acceptance: loopback world>=4 numerics are IDENTICAL to the
    world=1 (single-controller) path — bit for bit, because the
    completing rank runs the very same compiled program over the same
    sub-mesh."""

    def test_allreduce_bit_identical_to_single_controller(self):
        n = 4
        rng = np.random.RandomState(7)
        vals = [rng.randn(37).astype(np.float32) * (10.0 ** (i - 2))
                for i in range(n)]
        ps = hvd.add_process_set([0, 1, 2, 3])
        try:
            ref = hvd.allreduce(
                hvd.per_rank([jnp.asarray(v) for v in vals],
                             process_set=ps),
                op=hvd.Sum, process_set=ps, name="parity_ref")
            ref = np.asarray(ref)
        finally:
            hvd.remove_process_set(ps)

        with hvd.loopback.world(n) as w:
            def body():
                out = hvd.allreduce(jnp.asarray(vals[hvd.rank()]),
                                    op=hvd.Sum, name="parity")
                return np.asarray(out)

            outs = _results(w.run(body))
        for o in outs:
            assert o.tobytes() == ref.tobytes(), "loopback numerics drifted"


class TestStepCaptureReplay:
    """ISSUE-10 satellite: PR-8's multi-process ``negotiate_step`` replay
    exercised for real at world=4 — 3-step capture-on/off parity plus a
    forced mid-step divergence fallback."""

    def test_three_step_parity_capture_on_off(self):
        def run_world(capture: bool):
            env = {"HVD_STEP_CAPTURE": "1" if capture else "0"}
            with hvd.loopback.world(4, extra_env=env) as w:
                def body():
                    r = hvd.rank()
                    vals = []
                    for step in range(4):
                        hvd.step_marker()
                        hs = [hvd.allreduce_async(
                                  jnp.full((4,), float(r + i + step)),
                                  op=hvd.Sum, name=f"t{i}")
                              for i in range(3)]
                        vals.append([np.asarray(h.result()) for h in hs])
                    hvd.step_marker()
                    cap = hvd.fusion_stats()["capture"]
                    svc = None
                    from horovod_tpu import engine_service
                    s = engine_service.get_service()
                    if s is not None:
                        svc = s.step_negotiations
                    return vals, cap, svc

                return _results(w.run(body, timeout=240))

        on = run_world(True)
        off = run_world(False)
        for (vals_on, cap, svc), (vals_off, _c, _s) in zip(on, off):
            assert cap["recorded_steps"] == 1, cap
            assert cap["replayed_steps"] == 3, cap
            # the replay really batched the step's negotiations into
            # negotiate_step rounds (one per replayed step)
            assert svc == 3, svc
            for a, b in zip(vals_on, vals_off):
                for x, y in zip(a, b):
                    assert x.tobytes() == y.tobytes(), \
                        "capture on/off numerics diverged"

    def test_forced_mid_step_divergence_falls_back(self):
        with hvd.loopback.world(
                4, extra_env={"HVD_STEP_CAPTURE": "1"}) as w:
            def body():
                r = hvd.rank()
                results = []
                for step in range(4):
                    hvd.step_marker()
                    # step 2 diverges: an extra differently-shaped tensor
                    count = 3 if step != 2 else 2
                    hs = [hvd.allreduce_async(
                              jnp.full((4,), float(r + i)), op=hvd.Sum,
                              name=f"d{i}")
                          for i in range(count)]
                    if step == 2:
                        hs.append(hvd.allreduce_async(
                            jnp.full((9,), float(r)), op=hvd.Sum,
                            name="odd"))
                    results.append([np.asarray(h.result()) for h in hs])
                hvd.step_marker()
                cap = hvd.fusion_stats()["capture"]
                return results, cap

            outs = _results(w.run(body, timeout=240))
        for results, cap in outs:
            assert cap["fallbacks"] >= 1, cap  # the divergence fell back
            # numerics stayed correct through the fallback
            assert np.allclose(results[2][0], 0 + 1 + 2 + 3)
            assert np.allclose(results[2][-1], 0 + 1 + 2 + 3)


class TestChaos:
    """ISSUE-10 chaos gate: HVD_FAULT_SPEC rank death at world=4 under
    loopback surfaces PeerFailureError on every survivor in < 5 s and
    drives elastic blacklist + re-form (ci.sh runs this class under
    HVD_DEBUG_INVARIANTS=1)."""

    def test_rank_death_fast_abort_world4(self):
        os.environ["HVD_FAULT_SPEC"] = "worker:crash:rank=2:at_step=3"
        _faults.refresh()
        try:
            with hvd.loopback.world(4, extra_env=FAST_HEALTH) as w:
                def body():
                    state = hvd.elastic.JaxState(step=0)
                    t0 = time.monotonic()
                    try:
                        for step in range(200):
                            hvd.allreduce(jnp.ones(2), op=hvd.Sum,
                                          name=f"s{step}")
                            state.step += 1
                            state.commit()  # rank 2 crashes at commit #3
                        return ("finished", None)
                    except PeerFailureError as e:
                        return ("peerfail", time.monotonic() - t0, str(e))

                outs = w.run(body, timeout=120, allow_failures=True)
            survivors = [o for o in outs if o.rank != 2]
            dead = next(o for o in outs if o.rank == 2)
            assert isinstance(dead.error, RankKilled), dead
            for o in survivors:
                assert o.error is None, o
                kind, dt, msg = o.result
                assert kind == "peerfail", o.result
                assert dt < 5.0, f"abort took {dt:.1f}s (budget 5s)"
                assert "rank 2" in msg, msg
        finally:
            os.environ.pop("HVD_FAULT_SPEC", None)
            _faults.refresh()

    def test_crash_on_cycle_thread_still_surfaces(self):
        """A crash injected at a site that runs on a rank-owned HELPER
        thread (svc.exchange: the negotiation cycle loop) must still
        emulate process death — beats cease, survivors abort fast, and
        the dying rank's own main thread unwinds as killed (this leaked
        a zombie rank with live beats before the code-review fix)."""
        # after=30: the rank must die AFTER its first beats were
        # observed — a rank dead before ever beating is (by design) only
        # covered by the stall/exchange deadline, not silence detection
        os.environ["HVD_FAULT_SPEC"] = "svc.exchange:crash:rank=1:after=30"
        _faults.refresh()
        try:
            with hvd.loopback.world(2, extra_env=FAST_HEALTH) as w:
                def body():
                    t0 = time.monotonic()
                    try:
                        for step in range(200):
                            hvd.allreduce(jnp.ones(2), op=hvd.Sum,
                                          name=f"c{step}")
                        return ("finished", None)
                    except PeerFailureError:
                        return ("peerfail", time.monotonic() - t0)

                outs = w.run(body, timeout=120, allow_failures=True)
            dead = next(o for o in outs if o.rank == 1)
            survivor = next(o for o in outs if o.rank == 0)
            assert isinstance(dead.error, RankKilled), dead
            assert survivor.error is None, survivor
            kind, dt = survivor.result
            assert kind == "peerfail", survivor.result
            assert dt < 5.0, f"abort took {dt:.1f}s (budget 5s)"
        finally:
            os.environ.pop("HVD_FAULT_SPEC", None)
            _faults.refresh()

    def test_rank_death_drives_elastic_reform(self):
        """Worker dies mid-elastic-run at world=2: the survivor restores
        committed state, the driver blacklists the dead host, and the
        round re-forms at world=1 — the full recovery chain in-process."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        disco = FixedHosts({"lb-hostA": 1, "lb-hostB": 1})
        crashed: list = []
        box: dict = {}

        def body():
            hvd.init()
            state = hvd.elastic.JaxState(step=0, sizes=[])

            @hvd.elastic.run
            def train(state):
                while state.step < 20:
                    out = hvd.allreduce(jnp.ones(1), op=hvd.Sum)
                    world = int(float(np.asarray(out).reshape(-1)[0]))
                    state.sizes = state.sizes + [world]
                    state.step += 1
                    if state.step == 6 and hvd.rank() == 1 and not crashed:
                        crashed.append(1)
                        raise RankKilled(1)  # simulated hard death
                    state.commit()
                return state.sizes

            sizes = train(state)
            if hvd.rank() == 0:
                box["sizes"] = sizes
            return len(sizes)

        results, ok = elastic_run(body, np=2, min_np=1, max_np=2,
                                  discovery=disco, timeout=60,
                                  extra_env=FAST_HEALTH)
        assert ok, results.error_message
        sizes = box.get("sizes")
        assert sizes is not None
        assert len(sizes) >= 20
        assert sizes[0] == 2 and sizes[-1] == 1, sizes
        assert sorted(set(sizes)) == [1, 2], sizes


class TestElastic:
    def test_elastic_grow_world(self):
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        disco = FixedHosts({"lb-grow-A": 1})
        box: dict = {}

        def body():
            hvd.init()
            state = hvd.elastic.JaxState(step=0, sizes=[])

            @hvd.elastic.run
            def train(state):
                while state.step < 12 or (2 not in state.sizes
                                          and state.step < 200):
                    out = hvd.allreduce(jnp.ones(2), op=hvd.Sum)
                    world = int(float(np.asarray(out).reshape(-1)[0]))
                    state.sizes = state.sizes + [world]
                    state.step += 1
                    if state.step == 2 and hvd.rank() == 0:
                        disco.set({"lb-grow-A": 1, "lb-grow-B": 1})
                    time.sleep(0.03)
                    state.commit()
                return state.sizes

            sizes = train(state)
            if hvd.rank() == 0:
                box["sizes"] = sizes
            return len(sizes)

        results, ok = elastic_run(body, np=1, min_np=1, max_np=2,
                                  discovery=disco, timeout=60)
        assert ok, results.error_message
        sizes = box.get("sizes")
        assert sizes is not None
        assert sizes[0] == 1 and sizes[-1] == 2, sizes
        assert sorted(set(sizes)) == [1, 2], sizes
        assert len(sizes) < 200, "world never grew"
