"""hvdsched schedule-exploration model tests.

The concurrency-core race matrix under controlled schedule exploration
(every model must be clean), the detector suite against known-bad
fixtures (every planted bug must be FOUND and must replay byte-for-byte
from its ``(seed, trace)``), and the pinned PR-3 / PR-6 regression
shapes: the unguarded variants reconstruct the two deadlocks those PRs
fixed, the guarded variants run the current protections
(``program_issue.issue_serialized``; result materialization before
consumer chaining) and must survive exploration.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from horovod_tpu.utils import invariants as inv  # noqa: E402
from tools.hvdsched import (  # noqa: E402
    SchedFailure,
    explore,
    models,
    run_model,
)


@pytest.fixture
def sched_check():
    """Route the invariants seam through the cooperative scheduler for
    one test, restoring the prior cached state exactly (mirrors the
    debug_invariants fixture in test_invariants.py). Also silences the
    runtime logger: the models deliberately simulate failures, and
    their ERROR lines are expected model output, not test noise."""
    prior = os.environ.get("HVD_SCHED_CHECK")
    os.environ["HVD_SCHED_CHECK"] = "1"
    inv.refresh()
    logger = logging.getLogger("horovod_tpu")
    prior_level = logger.level
    logger.setLevel(logging.CRITICAL)
    yield inv
    if prior is None:
        os.environ.pop("HVD_SCHED_CHECK", None)
    else:
        os.environ["HVD_SCHED_CHECK"] = prior
    inv.refresh()
    logger.setLevel(prior_level)


# ---------------------------------------------------------------------------
# detectors + byte-for-byte replay
# ---------------------------------------------------------------------------

class TestDetectors:
    def test_deadlock_found_named_and_replayed(self, sched_check):
        result = explore(models.DEMOS["deadlock-demo"], schedules=60,
                         seed=0)
        assert not result.ok, result.summary()
        f = result.findings[0]
        assert f.kind == "deadlock"
        # the report names both locks of the inversion and both tasks
        text = str(f)
        assert "demo.a" in text and "demo.b" in text
        assert "t1" in text and "t2" in text
        # byte-for-byte replay from (seed, trace)
        with pytest.raises(SchedFailure) as exc:
            run_model(models.DEMOS["deadlock-demo"], seed=f.seed,
                      trace=f.trace)
        f2 = exc.value
        assert f2.kind == f.kind
        assert f2.trace == f.trace
        assert f2.report == f.report

    def test_lost_wakeup_found_only_under_exploration(self, sched_check):
        # the default schedule is clean — the missed-signal window
        # needs a specific preemption that only exploration forces
        run_model(models.DEMOS["lost-wakeup-demo"], seed=0)
        result = explore(models.DEMOS["lost-wakeup-demo"], schedules=60,
                         seed=0)
        assert not result.ok
        f = result.findings[0]
        assert f.kind == "lost-wakeup"
        assert "demo.cv" in str(f)
        with pytest.raises(SchedFailure) as exc:
            run_model(models.DEMOS["lost-wakeup-demo"], seed=f.seed,
                      trace=f.trace)
        assert exc.value.kind == "lost-wakeup"

    def test_livelock_detector(self, sched_check):
        def spin():
            lock = inv.make_lock("spin.lock")
            stop = []

            def spinner():
                while not stop:
                    with lock:
                        pass

            t = inv.spawn_thread(spinner, name="spinner", daemon=False)
            inv.join_thread(t)

        with pytest.raises(SchedFailure) as exc:
            run_model(spin, seed=0, max_steps=300)
        assert exc.value.kind == "livelock"

    def test_lock_leak_is_reported_not_masked(self, sched_check):
        # exiting while holding a lock is a permanent deadlock in real
        # threading; the runtime must flag it, not silently release
        def leak():
            lock = inv.make_lock("leak.lock")

            def holder():
                lock.acquire()  # BUG: never released

            t = inv.spawn_thread(holder, name="holder")
            inv.join_thread(t)

        with pytest.raises(SchedFailure) as exc:
            run_model(leak, seed=0)
        assert exc.value.kind == "lock-leak"
        assert "leak.lock" in str(exc.value)

    def test_model_exception_propagates(self, sched_check):
        def boom():
            raise ValueError("model bug, not a schedule finding")

        with pytest.raises(ValueError, match="model bug"):
            run_model(boom, seed=0)

    def test_model_assertion_becomes_replayable_finding(self, sched_check):
        # a model CONTRACT assertion is a schedule finding: it must
        # carry (seed, trace) so the explorer/CI gate can replay it,
        # unlike an arbitrary exception (a bug in the model itself)
        def broken_contract():
            ev = inv.make_event("contract.ev")
            if not ev.wait(5.0):  # nobody ever sets it
                raise AssertionError("entry never settled")

        with pytest.raises(SchedFailure) as exc:
            run_model(broken_contract, seed=7)
        assert exc.value.kind == "model-assertion"
        assert "entry never settled" in str(exc.value)
        assert exc.value.seed == 7
        # and it replays byte-for-byte
        with pytest.raises(SchedFailure) as exc2:
            run_model(broken_contract, seed=exc.value.seed,
                      trace=exc.value.trace)
        assert exc2.value.kind == "model-assertion"
        assert exc2.value.trace == exc.value.trace

    def test_virtual_clock_runs_fast(self, sched_check):
        # 1000 virtual seconds of sleeping must not take wall time
        def sleeper():
            inv.sleep(500.0)
            inv.sleep(500.0)

        res = run_model(sleeper, seed=0)
        assert res.clock >= 1000.0

    def test_seeded_runs_are_deterministic(self, sched_check):
        r1 = run_model(models.MATRIX["pr6-chain-guard"], seed=11)
        r2 = run_model(models.MATRIX["pr6-chain-guard"], seed=11)
        assert r1.trace == r2.trace


# ---------------------------------------------------------------------------
# the clean race matrix
# ---------------------------------------------------------------------------

class TestRaceMatrix:
    @pytest.mark.parametrize("name", sorted(models.MATRIX))
    def test_matrix_model_clean_under_exploration(self, sched_check, name):
        result = explore(models.MATRIX[name], schedules=25, seed=0)
        assert result.ok, (
            f"{name} should be schedule-clean, found:\n"
            + str(result.findings[0]))
        assert result.runs == 25


# ---------------------------------------------------------------------------
# pinned PR-3 / PR-6 regression shapes
# ---------------------------------------------------------------------------

class TestPinnedRegressions:
    def test_pr3_rendezvous_interleaving(self, sched_check):
        """The PR-3 shape: interleaved multi-device program launches
        cross the device queues and deadlock the rendezvous. Unguarded
        must be found; the real issue_serialized guard must hold."""
        bad = explore(models.DEMOS["pr3-unguarded"], schedules=60, seed=0)
        assert not bad.ok, "PR-3 deadlock shape no longer reproduces"
        f = bad.findings[0]
        assert "rendezvous" in str(f)
        with pytest.raises(SchedFailure):  # pinned replay
            run_model(models.DEMOS["pr3-unguarded"], seed=f.seed,
                      trace=f.trace)
        good = explore(models.MATRIX["pr3-issue-lock"], schedules=40,
                       seed=0)
        assert good.ok, (
            "program_issue.issue_serialized no longer prevents the PR-3 "
            "rendezvous deadlock:\n" + str(good.findings[0]))

    def test_pr6_chain_starvation(self, sched_check):
        """The PR-6 shape: consumers chained on an in-flight chunked
        collective occupy the execution pool and starve its remaining
        chunks. Unguarded must be found; materialize-before-chain (the
        HVD_EAGER_CHAIN auto-disable) must hold."""
        bad = explore(models.DEMOS["pr6-unguarded"], schedules=60, seed=0)
        assert not bad.ok, "PR-6 starvation shape no longer reproduces"
        f = bad.findings[0]
        assert "collective.result" in str(f)
        with pytest.raises(SchedFailure):  # pinned replay
            run_model(models.DEMOS["pr6-unguarded"], seed=f.seed,
                      trace=f.trace)
        good = explore(models.MATRIX["pr6-chain-guard"], schedules=40,
                       seed=0)
        assert good.ok, str(good.findings) if good.findings else ""


# ---------------------------------------------------------------------------
# loopback world rendezvous (ISSUE 10)
# ---------------------------------------------------------------------------

class TestLoopbackExchange:
    """The loopback world's execution substrate under controlled
    concurrency: the real hub must explore clean (it is also in the
    MATRIX sweep above), and the planted unguarded-rendezvous bug must
    be FOUND and replay byte-for-byte — world>1 chaos findings are
    (seed, trace)-replayable instead of flaky (ISSUE-10 acceptance)."""

    def test_unguarded_rendezvous_found_and_replays_byte_for_byte(
            self, sched_check):
        # the default schedule is clean: only exploration forces the
        # check-vs-wait preemption window
        run_model(models.DEMOS["loopback-exchange-unguarded"], seed=0)
        result = explore(models.DEMOS["loopback-exchange-unguarded"],
                         schedules=80, seed=0)
        assert not result.ok, "planted loopback rendezvous bug not found"
        f = result.findings[0]
        assert f.kind == "lost-wakeup"
        assert "lbdemo.cv" in str(f)
        # byte-for-byte (seed, trace) replay: identical kind, decision
        # trace, and report text
        with pytest.raises(SchedFailure) as exc:
            run_model(models.DEMOS["loopback-exchange-unguarded"],
                      seed=f.seed, trace=f.trace)
        f2 = exc.value
        assert f2.kind == f.kind
        assert f2.trace == f.trace
        assert f2.report == f.report

    def test_poisoned_round_outcomes_are_settled(self, sched_check):
        # single-run sanity beyond the matrix sweep: a poison racing
        # round 0 settles every rank with a result or the poison error
        run_model(models.MATRIX["loopback-exchange"], seed=3)


# ---------------------------------------------------------------------------
# autoscale decision vs re-form (ISSUE 15)
# ---------------------------------------------------------------------------

class TestAutoscaleDecision:
    """The autoscale policy's round-tag contract under controlled
    concurrency: the guarded shape (evaluate tags the round, apply
    re-validates atomically) explores clean — it is also in the MATRIX
    sweep — while the planted unguarded eviction must be FOUND evicting
    the replacement that inherited a re-formed slot, and must replay
    byte-for-byte from its (seed, trace)."""

    def test_guarded_decision_clean_single_run(self, sched_check):
        run_model(models.MATRIX["autoscale-decision"], seed=7)

    def test_unguarded_evict_found_and_replays(self, sched_check):
        # the default schedule is clean: only exploration forces the
        # re-form into the evaluate->apply window
        run_model(models.DEMOS["evict-during-reform-demo"], seed=0)
        result = explore(models.DEMOS["evict-during-reform-demo"],
                         schedules=60, seed=0)
        assert not result.ok, "planted stale-round eviction not found"
        f = result.findings[0]
        assert f.kind == "model-assertion"
        assert "stale-round eviction" in str(f)
        with pytest.raises(SchedFailure) as exc:
            run_model(models.DEMOS["evict-during-reform-demo"],
                      seed=f.seed, trace=f.trace)
        f2 = exc.value
        assert f2.kind == f.kind
        assert f2.trace == f.trace
        assert f2.report == f.report


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestCli:
    def _run(self, *args):
        env = dict(os.environ, HVD_SCHED_CHECK="1")
        return subprocess.run(
            [sys.executable, "-m", "tools.hvdsched", *args],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=300)

    def test_list(self):
        proc = self._run("--list")
        assert proc.returncode == 0, proc.stderr
        assert "pr3-issue-lock [matrix]" in proc.stdout
        assert "deadlock-demo [demo]" in proc.stdout

    def test_demo_gate_finds_planted_bug(self):
        proc = self._run("--demos", "--model", "deadlock-demo",
                         "--schedules", "40")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "FOUND" in proc.stdout
        assert "seed=" in proc.stdout and "trace=" in proc.stdout

    def test_unknown_model_is_usage_error(self):
        proc = self._run("--model", "no-such-model")
        assert proc.returncode == 2
