"""Hierarchical negotiation control plane + coordinator ResponseCache.

ISSUE-13 coverage (docs/negotiation.md): static group-layout edge cases
(G ∤ world), the two-level member → leader → cross-leader → fan-down
exchange against a real KV server, the coordinator ResponseCache's
confirm-then-serve lifecycle with its invalidation paths (knob-override
epoch, pset change / service reset, re-form via coordinated abort) and
bit-vector-divergence re-negotiation, flat ↔ hierarchical numerics
parity at world=4, leader-death chaos, and the world=16 tier-1 smoke
(world=64 marked slow, swept by ci.sh).
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import _native
from horovod_tpu.dynamic import NativeEngine, REQ_ALLREDUCE, REQ_ALLGATHER
from horovod_tpu.exceptions import PeerFailureError
from horovod_tpu.loopback.context import RankKilled
from horovod_tpu.negotiation import GroupLayout, ResponseCache
from horovod_tpu.negotiation import response_cache as rcache_mod
from horovod_tpu.utils import envs
from horovod_tpu.utils import faults as _faults

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_HEALTH = {"HVD_HEALTH_INTERVAL": "0.3", "HVD_HEALTH_TIMEOUT": "1.5"}
HIER_G2 = {"HVD_HIER_NEGOTIATION": "1", "HVD_NEGOTIATION_GROUP_SIZE": "2"}


# ---------------------------------------------------------------------------
# static group layout
# ---------------------------------------------------------------------------

class TestGroupLayout:
    def test_divisible(self):
        l = GroupLayout(8, 4)
        assert l.n_groups == 2
        assert l.leaders() == [0, 4]
        assert list(l.members_of(0)) == [0, 1, 2, 3]
        assert list(l.members_of(1)) == [4, 5, 6, 7]
        assert [l.group_of(r) for r in range(8)] == [0] * 4 + [1] * 4
        assert [l.is_leader(r) for r in range(8)] == \
            [True, False, False, False, True, False, False, False]

    def test_ragged_last_group(self):
        """G ∤ world: the last group is short; a one-member group leads
        itself."""
        l = GroupLayout(10, 4)
        assert l.n_groups == 3
        assert l.leaders() == [0, 4, 8]
        assert list(l.members_of(2)) == [8, 9]
        l1 = GroupLayout(9, 4)
        assert list(l1.members_of(2)) == [8]
        assert l1.is_leader(8)

    def test_degenerate_shapes(self):
        # G >= world: one group, rank 0 leads everyone
        l = GroupLayout(4, 8)
        assert l.n_groups == 1 and l.leaders() == [0]
        assert list(l.members_of(0)) == [0, 1, 2, 3]
        # G == 1: every rank is its own leader (pure cross-leader round)
        l1 = GroupLayout(4, 1)
        assert l1.n_groups == 4 and l1.leaders() == [0, 1, 2, 3]
        assert all(l1.is_leader(r) for r in range(4))
        # world == 1
        l2 = GroupLayout(1, 8)
        assert l2.n_groups == 1 and l2.is_leader(0)

    def test_partition_is_total_and_disjoint(self):
        for world, g in [(7, 3), (16, 8), (64, 8), (5, 5), (6, 4)]:
            l = GroupLayout(world, g)
            seen = []
            for gid in range(l.n_groups):
                members = list(l.members_of(gid))
                assert members[0] == l.leader_of(gid)
                for r in members:
                    assert l.group_of(r) == gid
                seen.extend(members)
            assert seen == list(range(world))

    def test_bounds_checked(self):
        l = GroupLayout(4, 2)
        with pytest.raises(ValueError):
            l.group_of(4)
        with pytest.raises(ValueError):
            l.members_of(2)
        with pytest.raises(ValueError):
            GroupLayout(0, 2)
        with pytest.raises(ValueError):
            GroupLayout(4, 0)


# ---------------------------------------------------------------------------
# coordinator ResponseCache: unit lifecycle
# ---------------------------------------------------------------------------

def _req(name="t", shape=(4,), rtype=REQ_ALLREDUCE, **kw):
    out = dict(name=name, request_type=rtype, dtype=0, element_size=4,
               shape=shape, root_rank=-1, group_id=-1, splits=(),
               reduce_op=-1, prescale=1.0, postscale=1.0, splits_crc=0)
    out.update(kw)
    return out


def _resp(name="t", from_cache=False):
    from horovod_tpu.dynamic import Response
    return Response(type=0, tensor_names=[name], from_cache=from_cache)


class TestResponseCacheUnit:
    def test_confirm_then_serve(self):
        rc = ResponseCache(8)
        req = _req()
        assert rc.lookup_confirmed(req) is None
        rc.note_response(req, _resp())  # fresh round: tentative
        assert rc.lookup_confirmed(req) is None
        rc.note_response(req, _resp(from_cache=True))  # AND-bit proof
        served = rc.lookup_confirmed(req)
        assert served is not None and served.tensor_names == ["t"]

    def test_signature_mismatch_never_serves(self):
        rc = ResponseCache(8)
        rc.note_response(_req(), _resp(from_cache=True))
        assert rc.lookup_confirmed(_req(shape=(5,))) is None
        assert rc.lookup_confirmed(_req(prescale=2.0)) is None
        assert rc.lookup_confirmed(_req(reduce_op=1)) is None
        assert rc.lookup_confirmed(_req()) is not None

    def test_uncacheable_types_skipped(self):
        rc = ResponseCache(8)
        for req in (_req(rtype=REQ_ALLGATHER),
                    _req(splits=(1, 2)),
                    _req(rtype=6)):  # barrier
            rc.note_response(req, _resp(from_cache=True))
            assert rc.lookup_confirmed(req) is None
        assert len(rc) == 0

    def test_error_and_fused_responses_not_cached(self):
        from horovod_tpu.dynamic import Response
        rc = ResponseCache(8)
        rc.note_response(_req(), Response(type=8, tensor_names=["t"],
                                          error_message="boom",
                                          from_cache=True))
        assert len(rc) == 0
        rc.note_response(_req(), Response(type=0, from_cache=True,
                                          tensor_names=["t", "u"]))
        assert len(rc) == 0

    def test_lru_capacity(self):
        rc = ResponseCache(2)
        for i in range(3):
            rc.note_response(_req(name=f"n{i}"),
                             _resp(name=f"n{i}", from_cache=True))
        assert len(rc) == 2
        assert rc.lookup_confirmed(_req(name="n0")) is None  # evicted
        assert rc.lookup_confirmed(_req(name="n2")) is not None

    def test_invalidate_and_drop(self):
        rc = ResponseCache(8)
        rc.note_response(_req(), _resp(from_cache=True))
        rc.note_response(_req(name="u"), _resp(name="u", from_cache=True))
        rc.drop_name("u")
        assert rc.lookup_confirmed(_req(name="u")) is None
        assert rc.lookup_confirmed(_req()) is not None
        assert rc.invalidate("test") == 1
        assert rc.lookup_confirmed(_req()) is None
        assert rc.stats()["invalidations"] == 1

    def test_capacity_zero_is_inert(self):
        rc = ResponseCache(0)
        rc.note_response(_req(), _resp(from_cache=True))
        assert rc.lookup_confirmed(_req()) is None
        assert len(rc) == 0


# ---------------------------------------------------------------------------
# hierarchical transport over a real KV server (no mesh programs)
# ---------------------------------------------------------------------------

class TestHierarchicalTransport:
    def _world(self, n, g, cycles=1):
        """Run `cycles` exchange rounds across n rank threads; returns
        each rank's (datas, bitvs, lags) per cycle."""
        from horovod_tpu.negotiation import HierarchicalTransport
        from horovod_tpu.runner.http_kv import KVServer, KVClient, \
            make_secret
        secret = make_secret()
        server = KVServer(secret=secret)
        port = server.start()
        out = [[None] * cycles for _ in range(n)]
        errors = []

        def rank_main(r):
            try:
                kv = KVClient("127.0.0.1", port, secret=secret)
                t = HierarchicalTransport(kv, n, r, prefix="t",
                                          group_size=g)
                for c in range(cycles):
                    datas, bitvs = t.exchange(
                        c, f"req{r}c{c}".encode(), bytes([r]), timeout=30)
                    out[r][c] = (datas, bitvs, dict(t.last_lags))
            except Exception as e:  # pragma: no cover - assertion aid
                errors.append((r, e))

        threads = [threading.Thread(target=rank_main, args=(r,),
                                    daemon=True) for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        server.stop()
        assert not errors, errors
        return out

    @pytest.mark.parametrize("n,g", [(4, 2), (5, 2), (6, 4), (3, 8)])
    def test_every_rank_gets_every_frame(self, n, g):
        out = self._world(n, g, cycles=2)
        for c in range(2):
            expect_datas = [f"req{r}c{c}".encode() for r in range(n)]
            expect_bits = [bytes([r]) for r in range(n)]
            for r in range(n):
                datas, bitvs, lags = out[r][c]
                assert datas == expect_datas, (r, c, datas)
                assert bitvs == expect_bits, (r, c, bitvs)
                # every member's server-receipt lag is attributed
                assert sorted(lags) == list(range(n)), lags
                assert min(lags.values()) == 0.0

    def test_matches_flat_transport(self):
        """Flat ↔ hierarchical parity: both transports deliver the
        identical rank-ordered (datas, bitvs) tables."""
        from horovod_tpu.engine_service import KVTransport
        from horovod_tpu.runner.http_kv import KVServer, KVClient, \
            make_secret
        n = 4
        secret = make_secret()
        server = KVServer(secret=secret)
        port = server.start()
        flat = [[None] for _ in range(n)]

        def rank_main(r):
            kv = KVClient("127.0.0.1", port, secret=secret)
            t = KVTransport(kv, n, r, prefix="flat")
            flat[r][0] = t.exchange(0, f"req{r}c0".encode(), bytes([r]),
                                    timeout=30)

        threads = [threading.Thread(target=rank_main, args=(r,),
                                    daemon=True) for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        server.stop()
        hier = self._world(n, 2, cycles=1)
        for r in range(n):
            assert flat[r][0][0] == hier[r][0][0]  # datas
            assert flat[r][0][1] == hier[r][0][1]  # bitvs


# ---------------------------------------------------------------------------
# service-level ResponseCache over in-memory lockstep transports
# ---------------------------------------------------------------------------

class _BarrierWorld:
    """In-memory lockstep exchange for N in-process DynamicServices."""

    def __init__(self, n):
        self.n = n
        self.cond = threading.Condition()
        self.frames: dict = {}
        self.closed = False

    def exchange(self, rank, cycle, req, bits, timeout):
        with self.cond:
            fr = self.frames.setdefault(cycle, {})
            fr[rank] = (req, bits)
            self.cond.notify_all()
            end = time.monotonic() + min(timeout, 30.0)
            while len(fr) < self.n:
                if self.closed:
                    raise RuntimeError("barrier world closed")
                if time.monotonic() > end:
                    raise TimeoutError(f"cycle {cycle} incomplete")
                self.cond.wait(0.2)
            self.frames.pop(cycle - 2, None)  # bound memory
            return ([fr[r][0] for r in range(self.n)],
                    [fr[r][1] for r in range(self.n)])

    def close(self):
        with self.cond:
            self.closed = True
            self.cond.notify_all()


class _BarrierTransport:
    def __init__(self, world, rank):
        self.world_mem = world
        self.world_size = world.n
        self.rank = rank

    def exchange(self, cycle, req, bits, timeout):
        return self.world_mem.exchange(self.rank, cycle, req, bits, timeout)


class TestServiceResponseCache:
    def _services(self, monkeypatch, n=2, cache="1", capacities=None):
        from horovod_tpu.engine_service import DynamicService
        monkeypatch.setenv("HVD_RESPONSE_CACHE", cache)
        world = _BarrierWorld(n)
        svcs = [DynamicService(
                    NativeEngine(world_size=n, rank=r,
                                 cache_capacity=(capacities[r]
                                                 if capacities else None)),
                    _BarrierTransport(world, r))
                for r in range(n)]
        return world, svcs

    def _negotiate_all(self, svcs, name, shape=(4,)):
        """All ranks negotiate `name` concurrently; returns responses."""
        results = [None] * len(svcs)
        errors = []

        def one(i):
            try:
                results[i] = svcs[i].negotiate(name, REQ_ALLREDUCE,
                                               shape=shape, timeout=30)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(len(svcs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(40)
        assert not errors, errors
        return results

    def _teardown(self, world, svcs):
        world.close()
        for s in svcs:
            s.stop()

    def _warm_until_confirmed(self, svcs, name, rounds=12):
        for _ in range(rounds):
            self._negotiate_all(svcs, name)
            if all(s.response_cache_stats()["confirmed"] >= 1
                   for s in svcs):
                return True
        return False

    def test_steady_state_serves_locally(self, monkeypatch):
        world, svcs = self._services(monkeypatch)
        try:
            assert self._warm_until_confirmed(svcs, "g"), \
                [s.response_cache_stats() for s in svcs]
            base = [s.response_cache_stats()["hits"] for s in svcs]
            for _ in range(3):
                resps = self._negotiate_all(svcs, "g")
                assert all(r.tensor_names == ["g"] for r in resps)
            for s, b in zip(svcs, base):
                st = s.response_cache_stats()
                assert st["hits"] == b + 3, st
        finally:
            self._teardown(world, svcs)

    def test_knob_epoch_invalidates(self, monkeypatch):
        world, svcs = self._services(monkeypatch)
        try:
            assert self._warm_until_confirmed(svcs, "e")
            self._negotiate_all(svcs, "e")  # served locally
            envs.set_override("CYCLE_TIME", "33")
            try:
                self._negotiate_all(svcs, "e")  # epoch bump: full round
                for s in svcs:
                    st = s.response_cache_stats()
                    assert st["invalidations"] >= 1, st
            finally:
                envs.clear_override("CYCLE_TIME")
        finally:
            self._teardown(world, svcs)

    def test_bit_vector_divergence_forces_renegotiation(self, monkeypatch):
        """A rank whose native cache cannot hold the entry (capacity 0)
        drops the AND-ed bit vector every cycle: responses never come
        back from_cache, no rank ever confirms, and every submission
        keeps taking a full negotiation round — divergence can never be
        served stale."""
        world, svcs = self._services(monkeypatch, capacities=[1024, 0])
        try:
            for _ in range(6):
                resps = self._negotiate_all(svcs, "d")
                assert all(not r.is_error for r in resps)
            for s in svcs:
                st = s.response_cache_stats()
                assert st["hits"] == 0, st
                assert st["confirmed"] == 0, st
                assert st["misses"] > 0, st
        finally:
            self._teardown(world, svcs)

    def test_metadata_change_renegotiates(self, monkeypatch):
        """Same name, new shape (the stream legitimately changed on
        every rank): the signature lookup misses, the new round replaces
        the entry, and the old response is never served."""
        world, svcs = self._services(monkeypatch)
        try:
            assert self._warm_until_confirmed(svcs, "m")
            resps = self._negotiate_all(svcs, "m", shape=(9,))
            assert all(not r.is_error for r in resps)
            # and the new shape can itself reach steady state
            ok = False
            for _ in range(12):
                self._negotiate_all(svcs, "m", shape=(9,))
                if all(s.response_cache_stats()["hits"] > 0 for s in svcs):
                    ok = True
                    break
            assert ok, [s.response_cache_stats() for s in svcs]
        finally:
            self._teardown(world, svcs)

    def test_stop_invalidates(self, monkeypatch):
        """Service stop/reset — the path every pset change and elastic
        re-form takes — drops the cache."""
        world, svcs = self._services(monkeypatch)
        try:
            assert self._warm_until_confirmed(svcs, "s")
        finally:
            self._teardown(world, svcs)
        for s in svcs:
            st = s.response_cache_stats()
            assert st["entries"] == 0, st
            assert st["invalidations"] >= 1, st

    def test_served_path_respects_duplicate_name_guard(self, monkeypatch):
        """A name still registered by an in-flight REAL negotiation must
        raise DuplicateNameError even when the cache could serve it —
        and the in-flight registration must survive untouched (a served
        ticket popping it would orphan the real waiter into the full
        exchange deadline)."""
        from horovod_tpu.dynamic import DuplicateNameError
        from horovod_tpu.engine_service import _Pending
        world, svcs = self._services(monkeypatch)
        try:
            assert self._warm_until_confirmed(svcs, "dup")
            svc = svcs[0]
            fake = _Pending()
            with svc._mu:
                svc._pending["dup"] = fake
            try:
                with pytest.raises(DuplicateNameError):
                    svc.negotiate("dup", REQ_ALLREDUCE, shape=(4,),
                                  timeout=5)
                with svc._mu:
                    assert svc._pending.get("dup") is fake, \
                        "served path touched the in-flight registration"
            finally:
                with svc._mu:
                    svc._pending.pop("dup", None)
        finally:
            self._teardown(world, svcs)

    def test_cache_off_is_flat_protocol(self, monkeypatch):
        world, svcs = self._services(monkeypatch, cache="0")
        try:
            for _ in range(3):
                self._negotiate_all(svcs, "off")
            for s in svcs:
                assert s.response_cache_stats() is None
        finally:
            self._teardown(world, svcs)

    def test_mid_job_flip_on_resize_off_via_knob_epoch(self, monkeypatch):
        """Default-on rollout ergonomics: HVD_RESPONSE_CACHE flips land
        at the next knob-override epoch with NO service rebuild — ON
        starts cold (standard confirmation rounds), RESIZE rebuilds at
        the new capacity, OFF drops every entry and the flat protocol
        keeps negotiating."""
        from horovod_tpu.utils import envs
        world, svcs = self._services(monkeypatch, cache="0")
        # Unpin the env var: an env-set knob is FIXED (overrides lose to
        # the environment) — mid-job flips are an override-epoch feature.
        monkeypatch.delenv("HVD_RESPONSE_CACHE")
        try:
            self._negotiate_all(svcs, "flip")
            for s in svcs:
                assert s._rcache is None
                assert s.response_cache_stats() is None

            envs.set_override("RESPONSE_CACHE", "1")
            assert self._warm_until_confirmed(svcs, "flip"), \
                [s.response_cache_stats() for s in svcs]
            base = [s.response_cache_stats()["hits"] for s in svcs]
            self._negotiate_all(svcs, "flip")
            for s, b in zip(svcs, base):
                assert s.response_cache_stats()["hits"] == b + 1

            envs.set_override("RESPONSE_CACHE", "64")
            self._negotiate_all(svcs, "flip")  # epoch applies at submit
            for s in svcs:
                assert s._rcache is not None and s._rcache.capacity == 64
                # resize = rebuilt cache: counters start from zero (the
                # still-warm NATIVE caches may re-confirm in one round,
                # but nothing has been SERVED from the new cache yet)
                assert s.response_cache_stats()["hits"] == 0

            envs.set_override("RESPONSE_CACHE", "0")
            for _ in range(2):
                resps = self._negotiate_all(svcs, "flip")
                assert all(r.tensor_names == ["flip"] for r in resps)
            for s in svcs:
                assert s._rcache is None
                assert s.response_cache_stats() is None
        finally:
            envs.clear_override("RESPONSE_CACHE")
            self._teardown(world, svcs)

    def test_auto_capacity_tracks_hierarchy_regime(self):
        """`auto` (the default) turns the cache on exactly in the
        pod-scale regime: world > HVD_NEGOTIATION_GROUP_SIZE."""
        from horovod_tpu.utils import envs
        group = envs.negotiation_group_size()
        assert envs.response_cache_capacity(None) == 0
        assert envs.response_cache_capacity(group) == 0
        assert (envs.response_cache_capacity(group * 2)
                == envs.DEFAULT_RESPONSE_CACHE_CAPACITY)


# ---------------------------------------------------------------------------
# loopback worlds: flat ↔ hierarchical parity, cache under join
# ---------------------------------------------------------------------------

class TestLoopbackHierarchy:
    def _run_world(self, extra):
        with hvd.loopback.world(4, extra_env=extra) as w:
            def body():
                r = hvd.rank()
                outs = []
                for step in range(5):
                    o = hvd.allreduce(jnp.full((4,), float(r + 1 + step)),
                                      op=hvd.Sum, name="p")
                    outs.append(np.asarray(o).tobytes())
                    g = hvd.grouped_allreduce(
                        [jnp.full((2,), float(r + i)) for i in range(2)],
                        op=hvd.Sum)
                    outs.extend(np.asarray(x).tobytes() for x in g)
                from horovod_tpu import engine_service
                svc = engine_service.get_service()
                return outs, type(svc.transport).__name__, \
                    (svc.response_cache_stats() or {})
            return [o.result for o in w.run(body)]

    def test_flat_hier_numerics_and_name_parity(self):
        """The same program at world=4 over the flat and the forced
        two-level control plane (with the ResponseCache on) produces
        byte-identical results on every rank — negotiation names are
        stable dispatch-plan names, so steady-state rounds confirm and
        serve from cache."""
        flat = self._run_world({"HVD_HIER_NEGOTIATION": "0",
                                "HVD_RESPONSE_CACHE": "0"})
        hier = self._run_world(dict(HIER_G2, HVD_RESPONSE_CACHE="1"))
        for r, (f, h) in enumerate(zip(flat, hier)):
            assert f[1] == "KVTransport", f[1]
            assert h[1] == "HierarchicalTransport", h[1]
            assert f[0] == h[0], f"rank {r} numerics diverged"
            assert h[2].get("hits", 0) > 0, h[2]

    def test_response_cache_with_join(self):
        """Joins end local serving (docs/negotiation.md "Joins"): JOIN
        itself is never cached, steady-state steps before the join serve
        locally, and the join completes with correct semantics — the
        join latch means an uneven tail AFTER a join always negotiates
        for real, so a joined rank's zero executions are never
        starved."""
        extra = {"HVD_RESPONSE_CACHE": "1"}
        with hvd.loopback.world(2, extra_env=extra) as w:
            def body():
                outs = []
                for step in range(5):
                    o = hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="j")
                    outs.append(float(np.asarray(o)[0]))
                from horovod_tpu import engine_service
                svc = engine_service.get_service()
                hits_before_join = svc.response_cache_stats()["hits"]
                hvd.join()
                # post-join uneven tail: rank 0 runs 2 more collectives
                # against the (re-armed) joined peer — these MUST take
                # real rounds (the latch), so the peer zero-contributes
                if hvd.rank() == 0:
                    for _ in range(2):
                        o = hvd.allreduce(jnp.ones(4), op=hvd.Sum,
                                          name="post")
                        outs.append(float(np.asarray(o)[0]))
                hvd.join()
                st = svc.response_cache_stats()
                return outs, hits_before_join, st["hits"]
            results = [o.result for o in w.run(body, timeout=240)]
        for r, (outs, hits_before, hits_after) in enumerate(results):
            assert outs[:5] == [2.0] * 5
            assert hits_before > 0, "no steady-state serving before join"
            assert hits_after == hits_before, \
                "local serving continued after a join"
        # rank 0's post-join tail reduced against the joined peer's zeros
        assert results[0][0][5:] == [1.0] * 2, results[0]


class TestChaosHierarchy:
    """ISSUE-13 chaos satellite: leader death mid-round surfaces
    PeerFailureError on every survivor within the watchdog budget, and a
    member is promotable on the next (re-formed) round."""

    def test_leader_death_fast_abort(self):
        os.environ["HVD_FAULT_SPEC"] = "worker:crash:rank=2:at_step=3"
        _faults.refresh()
        try:
            extra = dict(HIER_G2, **FAST_HEALTH)
            with hvd.loopback.world(4, extra_env=extra) as w:
                def body():
                    state = hvd.elastic.JaxState(step=0)
                    t0 = time.monotonic()
                    try:
                        for step in range(200):
                            hvd.allreduce(jnp.ones(2), op=hvd.Sum,
                                          name=f"s{step}")
                            state.step += 1
                            state.commit()  # rank 2 (a LEADER) dies here
                        return ("finished", None, None)
                    except PeerFailureError as e:
                        return ("peerfail", time.monotonic() - t0, str(e))

                outs = w.run(body, timeout=120, allow_failures=True)
            dead = next(o for o in outs if o.rank == 2)
            assert isinstance(dead.error, RankKilled), dead
            for o in outs:
                if o.rank == 2:
                    continue
                kind, dt, msg = o.result
                assert kind == "peerfail", o.result
                assert dt < 5.0, f"abort took {dt:.1f}s (budget 5s)"
                assert "rank 2" in msg, msg
        finally:
            os.environ.pop("HVD_FAULT_SPEC", None)
            _faults.refresh()

    def test_leader_death_promotes_member_on_reform(self):
        """Elastic loopback at world=2 with one-rank groups (every rank
        a leader): the leader of group 1 dies, the driver blacklists and
        re-forms at world=1, and the re-derived layout promotes the
        survivor to (sole) leader — training completes."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run
        from horovod_tpu.negotiation.layout import GroupLayout

        disco = FixedHosts({"lb-hA": 1, "lb-hB": 1})
        crashed: list = []
        box: dict = {}

        def body():
            hvd.init()
            state = hvd.elastic.JaxState(step=0, sizes=[])

            @hvd.elastic.run
            def train(state):
                while state.step < 16:
                    out = hvd.allreduce(jnp.ones(1), op=hvd.Sum)
                    state.sizes = state.sizes + [
                        int(float(np.asarray(out).reshape(-1)[0]))]
                    state.step += 1
                    if state.step == 5 and hvd.rank() == 1 and not crashed:
                        crashed.append(1)
                        raise RankKilled(1)
                    state.commit()
                return state.sizes

            sizes = train(state)
            if hvd.rank() == 0:
                layout = GroupLayout(hvd.size(), 1)
                box["sizes"] = sizes
                box["leads_after_reform"] = layout.is_leader(hvd.rank())
            return len(sizes)

        extra = dict(FAST_HEALTH, HVD_HIER_NEGOTIATION="1",
                     HVD_NEGOTIATION_GROUP_SIZE="1")
        results, ok = elastic_run(body, np=2, min_np=1, max_np=2,
                                  discovery=disco, timeout=60,
                                  extra_env=extra)
        assert ok, getattr(results, "error_message", results)
        assert box.get("sizes") is not None
        assert box["sizes"][-1] == 1 and box["sizes"][0] == 2
        assert box["leads_after_reform"] is True


# ---------------------------------------------------------------------------
# world=16 smoke (tier-1) and world=64 (slow; ci.sh second pass)
# ---------------------------------------------------------------------------

def _run_subworld(script: str, devices: int, timeout: float) -> str:
    env = dict(os.environ)
    env.pop("HVD_FAULT_SPEC", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", script], cwd=_REPO,
                          env=env, capture_output=True, text=True,
                          timeout=timeout)
    assert proc.returncode == 0, \
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-3000:]}"
    return proc.stdout


_W16_SCRIPT = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.utils import envs

n = 16
assert envs.hier_negotiation_enabled(n)  # auto: 16 > default group of 8
with hvd.loopback.world(n, extra_env={"HVD_RESPONSE_CACHE": "1"}) as w:
    def body():
        r = hvd.rank()
        outs = []
        for step in range(4):
            o = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                              name="g")
            outs.append(np.asarray(o))
        g = hvd.grouped_allreduce(
            [jnp.full((2,), float(r)), jnp.ones(3)], op=hvd.Sum)
        from horovod_tpu import engine_service
        svc = engine_service.get_service()
        return (outs, [np.asarray(x) for x in g],
                type(svc.transport).__name__,
                svc.response_cache_stats())
    res = w.run(body)
    expect = float(sum(range(1, n + 1)))
    for o in res:
        outs, g, tname, st = o.result
        assert tname == "HierarchicalTransport", tname
        assert all(np.allclose(x, expect) for x in outs), outs
        assert np.allclose(g[0], float(sum(range(n)))), g
        assert np.allclose(g[1], float(n)), g
        assert st["hits"] > 0, st
print("W16_OK")
"""


class TestWorld16Smoke:
    def test_world16_hier_cache_smoke(self):
        """Tier-1 world=16 smoke: a fresh interpreter with 16 virtual
        devices runs a 16-rank loopback world on the auto-engaged
        hierarchical control plane with the ResponseCache on — numerics
        exact, steady-state hits recorded."""
        out = _run_subworld(_W16_SCRIPT, devices=16, timeout=420)
        assert "W16_OK" in out, out


_W64_SCRIPT = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
import horovod_tpu as hvd

n = 64

def run_world(capture):
    extra = {"HVD_RESPONSE_CACHE": "1",
             "HVD_STEP_CAPTURE": "1" if capture else "0"}
    with hvd.loopback.world(n, extra_env=extra) as w:
        def body():
            r = hvd.rank()
            vals = []
            for step in range(3):
                hvd.step_marker()
                hs = [hvd.allreduce_async(
                          jnp.full((4,), float(r + i + step)),
                          op=hvd.Sum, name=f"t{i}") for i in range(2)]
                vals.append([np.asarray(h.result()).tobytes() for h in hs])
            hvd.step_marker()
            from horovod_tpu import engine_service
            svc = engine_service.get_service()
            return vals, type(svc.transport).__name__
        return [o.result for o in w.run(body)]

on = run_world(True)
off = run_world(False)
for (v_on, t_on), (v_off, t_off) in zip(on, off):
    assert t_on == t_off == "HierarchicalTransport"
    assert v_on == v_off, "capture on/off numerics diverged at world=64"
print("W64_OK")
"""


@pytest.mark.slow
class TestWorld64:
    def test_world64_capture_parity(self):
        """ISSUE-13 acceptance: a world=64 loopback world (8 leader
        groups of 8) completes capture-on/off-parity training steps."""
        out = _run_subworld(_W64_SCRIPT, devices=64, timeout=900)
        assert "W64_OK" in out, out


# ---------------------------------------------------------------------------
# loopback scale fixes (ISSUE-13 satellite)
# ---------------------------------------------------------------------------

class TestLoopbackScaleFixes:
    def test_hub_shards_isolate_slots(self):
        """Unrelated slots rendezvous on unrelated shard conditions; a
        burst of distinct collectives across many threads completes with
        no cross-slot interference and an empty registry after."""
        from horovod_tpu.loopback.hub import LoopbackHub
        hub = LoopbackHub("t")
        n, slots = 4, 24
        results = [[None] * slots for _ in range(n)]

        def rank_main(r):
            for s in range(slots):
                results[r][s] = hub.exchange_compute(
                    ("slot", s), r, n, r + s, lambda vals: sum(vals),
                    timeout=30)

        threads = [threading.Thread(target=rank_main, args=(r,),
                                    daemon=True) for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        for r in range(n):
            for s in range(slots):
                assert results[r][s] == sum(range(n)) + n * s
        assert hub.pending() == 0

    def test_hub_fail_all_sweeps_every_shard(self):
        from horovod_tpu.loopback.hub import LoopbackHub
        hub = LoopbackHub("t")
        errs = []

        def waiter(s):
            try:
                hub.exchange(("s", s), 0, 2, "x", timeout=30)
            except RuntimeError as e:
                errs.append(str(e))

        threads = [threading.Thread(target=waiter, args=(s,), daemon=True)
                   for s in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        hub.fail_all(RuntimeError("teardown"))
        for t in threads:
            t.join(10)
        assert len(errs) == 8 and all("teardown" in e for e in errs)
        with pytest.raises(RuntimeError):
            hub.exchange(("s", 99), 0, 2, "x", timeout=1)

    def test_xseq_lru_cap_deterministic(self):
        """The occurrence table is capped per scope and evicts in
        insertion order — the same order on every member rank."""
        from horovod_tpu.loopback import dispatch as lbd
        from horovod_tpu.loopback.context import RankContext

        ctx = RankContext(world=None, rank=0)
        scope = ("addr", "0", "0", (0, 1))
        cap = lbd._XSEQ_CAP
        for i in range(cap + 10):
            assert lbd._next_occurrence(ctx, scope, f"n{i}") == 0
        table = ctx.xseq[scope]
        assert len(table) == cap
        assert "n0" not in table and f"n{cap + 9}" in table
        # a surviving hot name keeps counting
        assert lbd._next_occurrence(ctx, scope, f"n{cap + 9}") == 1

    def test_xseq_stale_scope_prune(self):
        from horovod_tpu.loopback import dispatch as lbd
        from horovod_tpu.loopback.context import RankContext

        ctx = RankContext(world=None, rank=0)
        ctx.env = {"HVD_COORDINATOR_ADDR": "new", "HVD_COORDINATOR_PORT": "2"}
        live = ("new", "2", "0", (0, 1))
        stale = ("old", "1", "0", (0, 1))
        obj_live = ("obj", "new", "2")
        obj_stale = ("obj", "old", "1")
        from horovod_tpu.loopback import context as lbctx
        for s in (live, stale, obj_live, obj_stale):
            ctx.xseq[s] = {"": 1}
        with lbctx.activate(ctx):
            lbd.prune_stale_scopes(ctx)
        assert set(ctx.xseq) == {live, obj_live}

    def test_loopback_timeout_scales_with_world(self, monkeypatch):
        from horovod_tpu.loopback import dispatch as lbd
        monkeypatch.delenv("HVD_LOOPBACK_TIMEOUT", raising=False)
        # outside any initialized runtime the small-world default holds
        assert lbd._timeout_s() == lbd.DEFAULT_LOOPBACK_TIMEOUT_S
        monkeypatch.setenv("HVD_LOOPBACK_TIMEOUT", "7.5")
        assert lbd._timeout_s() == 7.5
