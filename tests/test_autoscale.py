"""Closed-loop elastic autoscaling (ISSUE 15; docs/elastic.md).

Unit coverage of the driver-side :class:`AutoscalePolicy` (decision
rules, hysteresis/cooldown, round-tag staleness, failure semantics) and
the per-rank commit observer, plus loopback end-to-end runs: an SLO
breach scales up without a script, sustained idle scales down with zero
steps lost, a fault-injected slow rank is evicted-and-replaced with the
blamed rank named in the decision instrument, and an adversarial
flapping load produces no oscillation.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import _native
from horovod_tpu import metrics as _metrics
from horovod_tpu.elastic import policy as policy_mod
from horovod_tpu.elastic.policy import AutoscalePolicy, sensor_key
from horovod_tpu.runner import hosts as hosts_mod
from horovod_tpu.utils import envs
from horovod_tpu.utils import faults as _faults

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")

FAST_HEALTH = {"HVD_HEALTH_INTERVAL": "0.2", "HVD_HEALTH_TIMEOUT": "2"}


@pytest.fixture
def fault_spec():
    def install(spec):
        os.environ["HVD_FAULT_SPEC"] = spec
        _faults.refresh()

    yield install
    os.environ.pop("HVD_FAULT_SPEC", None)
    _faults.refresh()
    _faults.clear_membership_handler()


# ---------------------------------------------------------------------------
# unit scaffolding: stub driver + in-memory KV
# ---------------------------------------------------------------------------

class _KV(dict):
    def put(self, k, v):
        self[k] = v

    def get(self, k):
        return dict.get(self, k)

    def keys(self, scope=""):
        prefix = scope.rstrip("/") + "/" if scope else ""
        return sorted(k for k in dict.keys(self) if k.startswith(prefix))


def _slot(rank, host, size=3):
    return hosts_mod.SlotInfo(hostname=host, rank=rank, size=size,
                              local_rank=0, local_size=1,
                              cross_rank=rank, cross_size=size)


class _StubRendezvous:
    def __init__(self):
        self.round_id = 1


class _StubDriver:
    def __init__(self, hosts_by_rank):
        self._rendezvous = _StubRendezvous()
        self._round_lock = threading.RLock()
        self._rank_assignments = {
            r: _slot(r, h, len(hosts_by_rank))
            for r, h in hosts_by_rank.items()}
        self.graced = []

    def world_size(self):
        return len(self._rank_assignments)

    def set_stale_grace(self, host, s):
        self.graced.append((host, s))

    def has_rank_assignment(self, host, slot):
        return any(s.hostname == host for s in
                   self._rank_assignments.values())


def _mk_policy(monkeypatch, driver=None, hosts=None, kv=None, *,
               min_np=2, max_np=4, slo_ms="100", breach=2, idle=2,
               evict=2, cooldown="0"):
    from horovod_tpu.elastic.discovery import FixedHosts
    monkeypatch.setenv("HVD_AUTOSCALE", "1")
    monkeypatch.setenv("HVD_AUTOSCALE_SLO_MS", slo_ms)
    monkeypatch.setenv("HVD_AUTOSCALE_BREACH_WINDOWS", str(breach))
    monkeypatch.setenv("HVD_AUTOSCALE_IDLE_WINDOWS", str(idle))
    monkeypatch.setenv("HVD_AUTOSCALE_EVICT_WINDOWS", str(evict))
    monkeypatch.setenv("HVD_AUTOSCALE_COOLDOWN", cooldown)
    driver = driver or _StubDriver({0: "h0", 1: "h1", 2: "h2"})
    hosts = hosts if hosts is not None else FixedHosts(
        {s.hostname: 1 for s in driver._rank_assignments.values()})
    kv = kv if kv is not None else _KV()
    return AutoscalePolicy(driver, hosts, kv, min_np=min_np,
                           max_np=max_np), driver, hosts, kv


def _blob(kv, rank, *, round_id=1, seq, steps=10, violations=0,
          step_s_mean=0.02, pending=0.0, straggler=None):
    kv.put(sensor_key(rank), json.dumps({
        "rank": rank, "round": round_id, "seq": seq, "steps": steps,
        "violations": violations, "step_s_mean": step_s_mean,
        "pending_bytes": pending, "qos_wait_s_mean": 0.0,
        "straggler": straggler or {}}).encode())


# ---------------------------------------------------------------------------
# decision rules
# ---------------------------------------------------------------------------

class TestPolicyRules:
    def test_scale_up_after_consecutive_breaches(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch)
        for r in range(3):
            _blob(kv, r, seq=1, violations=8)
        assert pol.tick() is None  # streak 1 of 2
        for r in range(3):
            _blob(kv, r, seq=2, violations=8)
        d = pol.tick()
        assert d is not None and (d.action, d.reason) == (
            "add", "slo-breach")
        assert "auto0" in hosts.find_available_hosts_and_slots()
        assert pol.policy_stats()["breach_streak"] == 0  # reset on act

    def test_breach_needs_majority_violation_share(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, breach=1)
        for r in range(3):
            _blob(kv, r, seq=1, steps=10,
                  violations=2 if r == 0 else 0)  # 2/30 < half
        assert pol.tick() is None
        assert pol.policy_stats()["breach_streak"] == 0

    def test_scale_up_respects_ceiling(self, monkeypatch):
        driver = _StubDriver({r: f"h{r}" for r in range(4)})
        pol, driver, hosts, kv = _mk_policy(monkeypatch, driver=driver,
                                            max_np=4, breach=1)
        for r in range(4):
            _blob(kv, r, seq=1, violations=9)
        assert pol.tick() is None  # at the ceiling: hold without decision
        assert "auto0" not in hosts.find_available_hosts_and_slots()

    def test_idle_scale_down_graceful_highest_rank(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, idle=2)
        for seq in (1, 2):
            for r in range(3):
                _blob(kv, r, seq=seq, violations=0, step_s_mean=0.01)
            d = pol.tick()
        assert d is not None and (d.action, d.reason) == ("remove", "idle")
        # highest-rank host departs with the grace window; rank 0 stays
        assert driver.graced and driver.graced[0][0] == "h2"
        assert "h2" not in hosts.find_available_hosts_and_slots()
        assert "h0" in hosts.find_available_hosts_and_slots()

    def test_idle_needs_every_rank_reporting(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, idle=1)
        for r in range(2):  # world is 3: one rank silent
            _blob(kv, r, seq=1, violations=0, step_s_mean=0.01)
        assert pol.tick() is None
        assert pol.policy_stats()["idle_streak"] == 0

    def test_scale_down_respects_floor(self, monkeypatch):
        driver = _StubDriver({0: "h0", 1: "h1"})
        pol, driver, hosts, kv = _mk_policy(monkeypatch, driver=driver,
                                            min_np=2, idle=1)
        for r in range(2):
            _blob(kv, r, seq=1, violations=0, step_s_mean=0.01)
        assert pol.tick() is None
        assert "h1" in hosts.find_available_hosts_and_slots()

    def test_evict_names_blamed_rank_and_replaces(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, evict=2)
        before = _metrics.ELASTIC_POLICY_DECISIONS.value(
            labels={"action": "evict", "reason": "straggler", "rank": "2"})
        for seq in (1, 2):
            for r in (0, 1):  # two survivors blame rank 2
                _blob(kv, r, seq=seq, straggler={"2": 3})
            d = pol.tick()
        assert d is not None and (d.action, d.reason, d.rank) == (
            "evict", "straggler", 2)
        live = hosts.find_available_hosts_and_slots()
        assert "h2" not in live and "auto0" in live  # replaced, same size
        assert driver.graced and driver.graced[0][0] == "h2"
        after = _metrics.ELASTIC_POLICY_DECISIONS.value(
            labels={"action": "evict", "reason": "straggler", "rank": "2"})
        assert after == before + 1  # the blamed rank is NAMED

    def test_evict_blame_streak_must_be_same_rank(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, evict=2)
        _blob(kv, 0, seq=1, straggler={"2": 3})
        assert pol.tick() is None
        _blob(kv, 0, seq=2, straggler={"1": 3})  # blame moved: streak resets
        assert pol.tick() is None
        assert pol.policy_stats()["blame"] == (1, 1)

    def test_refuses_to_evict_rank0(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, evict=1)
        _blob(kv, 1, seq=1, straggler={"0": 5})
        d = pol.tick()
        assert d is not None and (d.action, d.reason) == (
            "hold", "protected")
        assert "h0" in hosts.find_available_hosts_and_slots()

    def test_protected_blame_never_starves_breach_rule(self, monkeypatch):
        """A sustained rank-0 blame hits the protected hold, which must
        RESET the blame streak — evict precedes breach in the decision
        order, so without the reset a slow rank 0 would hold scale-up
        out forever while the SLO burns."""
        pol, driver, hosts, kv = _mk_policy(monkeypatch, evict=2,
                                            breach=3)
        for seq in (1, 2):
            _blob(kv, 0, seq=seq, violations=8, straggler={"0": 5})
            d = pol.tick()
        assert d is not None and (d.action, d.reason) == (
            "hold", "protected")
        assert pol.policy_stats()["blame"] == (None, 0)
        # breach streak kept accumulating through the protected windows:
        # the next breach window scales up even though blame continues
        _blob(kv, 0, seq=3, violations=8, straggler={"0": 5})
        d = pol.tick()
        assert d is not None and (d.action, d.reason) == (
            "add", "slo-breach")

    def test_remove_never_breaks_floor_with_multislot_host(
            self, monkeypatch):
        """Removing a host removes ALL its slots: a 2-slot victim at
        world 4 with floor 3 must hold, not punch through to 2."""
        driver = _StubDriver({0: "h0", 1: "h0", 2: "h1", 3: "h1"})
        pol, driver, hosts, kv = _mk_policy(monkeypatch, driver=driver,
                                            min_np=3, idle=1)
        for r in range(4):
            _blob(kv, r, seq=1, violations=0, step_s_mean=0.01)
        d = pol.tick()
        assert d is not None and (d.action, d.reason) == (
            "hold", "protected")
        assert "h1" in hosts.find_available_hosts_and_slots()

    def test_evict_replacement_matches_victim_slot_count(
            self, monkeypatch):
        """Evict-and-replace keeps the world size even for a multi-slot
        victim host: the replacement offers the same slot count."""
        driver = _StubDriver({0: "h0", 1: "h1", 2: "h1"})
        pol, driver, hosts, kv = _mk_policy(monkeypatch, driver=driver,
                                            evict=1)
        _blob(kv, 0, seq=1, straggler={"2": 5})
        d = pol.tick()
        assert d is not None and (d.action, d.reason, d.rank) == (
            "evict", "straggler", 2)
        live = hosts.find_available_hosts_and_slots()
        assert "h1" not in live and live.get("auto0") == 2

    def test_apply_blocked_by_inflight_reform_holds(self, monkeypatch):
        """The apply guard never blocks on the driver's round lock (a
        parked resume holds it while depending on discovery): a busy
        lock means a re-form owns the round — degrade to a hold."""
        pol, driver, hosts, kv = _mk_policy(monkeypatch, evict=1)
        _blob(kv, 0, seq=1, straggler={"2": 5})
        acquired, release = threading.Event(), threading.Event()

        def holder():
            with driver._round_lock:
                acquired.set()
                release.wait(10)

        t = threading.Thread(target=holder)
        t.start()
        assert acquired.wait(5)
        try:
            d = pol.tick()
        finally:
            release.set()
            t.join()
        assert d is not None and (d.action, d.reason) == (
            "hold", "stale-round")
        assert "h2" in hosts.find_available_hosts_and_slots()


# ---------------------------------------------------------------------------
# robustness contract: round tags, staleness, eval failure, oscillation
# ---------------------------------------------------------------------------

class TestPolicyRobustness:
    def test_stale_round_decision_is_noop(self, monkeypatch):
        """A decision evaluated against round R applied after the world
        re-formed to R+1 must hold — not mutate membership (the ISSUE 15
        round-tag contract)."""
        pol, driver, hosts, kv = _mk_policy(monkeypatch, evict=1)
        _blob(kv, 0, seq=1, straggler={"2": 5})
        orig = pol._stale

        def reform_then_check(round_id):
            driver._rendezvous.round_id = 2  # re-form lands mid-apply
            return orig(round_id)

        monkeypatch.setattr(pol, "_stale", reform_then_check)
        d = pol.tick()
        assert d is not None and (d.action, d.reason) == (
            "hold", "stale-round")
        assert "h2" in hosts.find_available_hosts_and_slots()

    def test_blaming_a_rank_that_left_is_noop(self, monkeypatch):
        """The blamed rank's assignment vanished (it just left): the
        eviction degrades to a counted hold — never removes whoever
        inherited the rank number."""
        pol, driver, hosts, kv = _mk_policy(monkeypatch, evict=1)
        _blob(kv, 0, seq=1, straggler={"2": 5})
        del driver._rank_assignments[2]
        d = pol.tick()
        assert d is not None and (d.action, d.reason, d.rank) == (
            "hold", "stale-round", 2)
        assert "h2" in hosts.find_available_hosts_and_slots()
        assert pol.policy_stats()["blame"] == (None, 0)

    def test_stale_sensor_round_ignored(self, monkeypatch):
        """Blobs tagged with a superseded round describe renumbered
        ranks — they must not feed a decision."""
        pol, driver, hosts, kv = _mk_policy(monkeypatch, breach=1)
        for r in range(3):
            _blob(kv, r, round_id=0, seq=1, violations=9)
        assert pol.tick() is None
        assert pol.policy_stats()["breach_streak"] == 0

    def test_eval_error_degrades_to_hold(self, monkeypatch, fault_spec):
        """A policy-evaluation error (here: injected at the policy.eval
        seam) records a typed hold/error decision and the next window
        runs clean — never a job failure."""
        pol, driver, hosts, kv = _mk_policy(monkeypatch, breach=1)
        fault_spec("policy.eval:error:times=1")
        d = pol.tick()
        assert d is not None and (d.action, d.reason) == ("hold", "error")
        assert "injected fault" in d.detail
        for r in range(3):
            _blob(kv, r, seq=1, violations=9)
        d2 = pol.tick()  # the next window decides normally
        assert d2 is not None and d2.action == "add"

    def test_sensor_garbage_degrades_to_hold(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, breach=1)
        kv.put(sensor_key(0), b"\xff not json")
        assert pol.tick() is None  # unparseable blob: skipped, no crash

    def test_cooldown_blocks_consecutive_actions(self, monkeypatch):
        pol, driver, hosts, kv = _mk_policy(monkeypatch, breach=1,
                                            cooldown="60")
        for r in range(3):
            _blob(kv, r, seq=1, violations=9)
        d = pol.tick()
        assert d is not None and d.action == "add"
        for r in range(3):
            _blob(kv, r, seq=2, violations=9)
        assert pol.tick() is None  # cooldown holds
        assert pol.policy_stats()["cooldown_remaining_s"] > 0

    def test_adversarial_flapping_produces_no_action(self, monkeypatch):
        """The hysteresis bound: a load alternating breach/idle every
        window never reaches either consecutive-window threshold — zero
        membership decisions over an arbitrary horizon."""
        pol, driver, hosts, kv = _mk_policy(monkeypatch, breach=2, idle=2)
        for seq in range(1, 13):
            breach = seq % 2 == 0
            for r in range(3):
                _blob(kv, r, seq=seq,
                      violations=9 if breach else 0,
                      step_s_mean=0.2 if breach else 0.01)
            assert pol.tick() is None, f"acted on flapping window {seq}"
        assert hosts.find_available_hosts_and_slots() == {
            "h0": 1, "h1": 1, "h2": 1}
        assert pol.policy_stats()["decisions"] == []


# ---------------------------------------------------------------------------
# worker-side observer
# ---------------------------------------------------------------------------

class TestCommitObserver:
    def test_observer_records_and_publishes(self, monkeypatch):
        monkeypatch.setenv("HVD_AUTOSCALE", "1")
        monkeypatch.setenv("HVD_AUTOSCALE_SLO_MS", "1")  # everything slow
        monkeypatch.setenv("HVD_AUTOSCALE_INTERVAL", "0.01")
        monkeypatch.setenv("HVD_RANK", "1")
        obs = policy_mod.CommitObserver()
        kv = _KV()
        obs._client = kv
        base_v = _metrics.ELASTIC_SLO_VIOLATIONS.value()
        obs.note()  # arms the clock
        time.sleep(0.02)
        obs.note()
        assert _metrics.ELASTIC_SLO_VIOLATIONS.value() == base_v + 1
        raw = kv.get(sensor_key(1))
        assert raw is not None
        blob = json.loads(raw.decode())
        assert blob["rank"] == 1 and blob["seq"] == 1
        assert blob["violations"] == 1 and blob["steps"] == 1
        assert blob["step_s_mean"] > 0
        assert "straggler" in blob and "pending_bytes" in blob

    def test_observer_publishes_blame_deltas(self, monkeypatch):
        monkeypatch.setenv("HVD_AUTOSCALE", "1")
        monkeypatch.setenv("HVD_AUTOSCALE_INTERVAL", "0.01")
        monkeypatch.setenv("HVD_RANK", "0")
        obs = policy_mod.CommitObserver()
        kv = _KV()
        obs._client = kv
        monkeypatch.setattr(policy_mod._health, "straggler_blames",
                            lambda: {3: 7})
        obs.note()
        time.sleep(0.02)
        obs.note()
        blob = json.loads(kv.get(sensor_key(0)).decode())
        assert blob["straggler"] == {"3": 7}
        # second window: no NEW blame rounds -> empty delta
        monkeypatch.setattr(policy_mod._health, "straggler_blames",
                            lambda: {3: 7})
        time.sleep(0.02)
        obs.note()
        time.sleep(0.02)
        obs.note()
        blob = json.loads(kv.get(sensor_key(0)).decode())
        assert blob["straggler"] == {}

    def test_note_commit_fast_path_when_disabled(self, monkeypatch):
        monkeypatch.delenv("HVD_AUTOSCALE", raising=False)
        policy_mod.reset_observer()
        policy_mod.note_commit()  # caches the disabled miss
        assert policy_mod._process_observer is False
        policy_mod.note_commit()
        policy_mod.reset_observer()

    def test_straggler_blames_reads_registry(self):
        from horovod_tpu import health
        _metrics.STRAGGLER_ROUNDS.inc(labels={"rank": 5})
        assert health.straggler_blames().get(5, 0) >= 1


# ---------------------------------------------------------------------------
# loopback end to end: the closed loop
# ---------------------------------------------------------------------------

def _autoscale_env(**over):
    env = dict(FAST_HEALTH)
    env.update({
        "HVD_AUTOSCALE": "1",
        "HVD_AUTOSCALE_INTERVAL": "0.4",
        "HVD_AUTOSCALE_COOLDOWN": "3",
        "HVD_AUTOSCALE_GRACE": "30",
    })
    env.update({k: str(v) for k, v in over.items()})
    return env


class TestClosedLoopLoopback:
    def test_evicted_straggler_replaced_warm_zero_steps_lost(
            self, fault_spec):
        """ISSUE 15 eviction semantics, end to end at world=3: a
        fault-injected slow rank is blamed by the StragglerTracker,
        the policy evicts its host through the PR-14 grace window (zero
        steps lost) while a replacement joins in the same re-form, the
        replacement adopts the shape-keyed warm shelves, and the blamed
        rank is named in the decision instrument."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        # rank 2 submits late on every busy round of round 1 only (the
        # replacement that inherits rank 2 after the re-form must not
        # inherit the fault); response cache off so every round is busy
        # and the tracker sees the lag.
        fault_spec("svc.exchange:delay=0.4:rank=2:at_round=1")
        disco = FixedHosts({"e0": 1, "e1": 1, "e2": 1})
        box, abox = {}, {}

        def body():
            hvd.init()
            state = hvd.elastic.JaxState(step=0, log=[])

            @hvd.elastic.run
            def train(state):
                from horovod_tpu.ops import dispatch_cache
                while state.step < 46:
                    out = hvd.allreduce(jnp.arange(4.0) + 1.0,
                                        op=hvd.Sum, name="w")
                    world = int(float(np.asarray(out).reshape(-1)[0]))
                    if hvd.rank() == 0:
                        state.log = state.log + [(
                            state.step, world,
                            float(np.asarray(out).reshape(-1)[1]),
                            dispatch_cache.stats()["warm_reuses"],
                            int(_metrics.ELASTIC_STEPS_LOST.value()))]
                    state.step += 1
                    state.commit()
                return state.log

            log = train(state)
            if hvd.rank() == 0:
                box["log"] = log
            return 0

        results, ok = elastic_run(
            body, np=3, min_np=2, max_np=4, discovery=disco, timeout=120,
            extra_env=_autoscale_env(
                HVD_RESPONSE_CACHE="0",
                HVD_STRAGGLER_THRESHOLD="0.15",
                HVD_AUTOSCALE_EVICT_WINDOWS="2"),
            autoscale_box=abox)
        assert ok, results.error_message
        log = box["log"]
        evicts = [d for d in abox.get("decisions", [])
                  if d["action"] == "evict"]
        assert evicts, f"no eviction decided: {abox.get('decisions')}"
        assert evicts[0]["reason"] == "straggler"
        assert evicts[0]["rank"] == 2  # the planted-slow rank, named
        # the decision landed in the instrument with the rank label
        assert _metrics.ELASTIC_POLICY_DECISIONS.value(labels={
            "action": "evict", "reason": "straggler", "rank": "2"}) >= 1
        # graceful departure: zero steps lost end to end
        assert log[-1][4] == 0, f"eviction lost steps: {log[-1]}"
        # the world re-formed once at the same size (evict+replace in
        # one discovery tick) and finished at 3
        worlds = [row[1] for row in log]
        assert worlds[-1] == 3, worlds
        # numerics parity at every logged step
        for step, world, p1, _wr, _lost in log:
            assert p1 == pytest.approx(2.0 * world), (step, world, p1)
        # committed steps never replay
        steps = [row[0] for row in log]
        assert steps == sorted(set(steps))
        # the replacement re-formed into a shelved shape: warm grafts
        assert log[-1][3] > 0, f"no warm reuse after eviction: {log[-1]}"

    def test_slo_breach_scales_up_idle_scales_down(self, fault_spec):
        """The closed loop without any script: heavy per-rank load at
        world=2 breaches the SLO and the policy grows the world; the
        load then drops, sustained idle shrinks it back to the floor
        with zero steps lost."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        disco = FixedHosts({"c0": 1, "c1": 1})
        box, abox = {}, {}

        def body():
            hvd.init()
            state = hvd.elastic.JaxState(step=0, log=[])

            @hvd.elastic.run
            def train(state):
                while state.step < 200:
                    out = hvd.allreduce(jnp.ones(2), op=hvd.Sum,
                                        name="w")
                    world = int(float(np.asarray(out).reshape(-1)[0]))
                    if hvd.rank() == 0:
                        state.log = state.log + [(
                            state.step, world,
                            int(_metrics.ELASTIC_STEPS_LOST.value()))]
                    # synthetic work model: fixed offered load shared by
                    # the world — the signal the loop must close on
                    if state.step < 60:
                        time.sleep(0.60 / world)  # breach at 2, ok at 3
                    else:
                        time.sleep(0.02)  # idle
                    state.step += 1
                    state.commit()
                return state.log

            log = train(state)
            if hvd.rank() == 0:
                box["log"] = log
            return 0

        results, ok = elastic_run(
            body, np=2, min_np=2, max_np=3, discovery=disco, timeout=180,
            extra_env=_autoscale_env(
                HVD_RESPONSE_CACHE="1",
                HVD_AUTOSCALE_SLO_MS="220",
                HVD_AUTOSCALE_BREACH_WINDOWS="2",
                HVD_AUTOSCALE_IDLE_WINDOWS="3",
                HVD_AUTOSCALE_IDLE_FACTOR="0.6"),
            autoscale_box=abox)
        assert ok, results.error_message
        log = box["log"]
        decisions = [(d["action"], d["reason"])
                     for d in abox.get("decisions", [])
                     if d["action"] != "hold"]
        assert ("add", "slo-breach") in decisions, decisions
        assert ("remove", "idle") in decisions, decisions
        worlds = [w for (_s, w, _l) in log]
        assert 3 in worlds, "scale-up never re-formed"
        assert worlds[-1] == 2, f"did not return to the floor: {worlds}"
        assert log[-1][2] == 0, "closed-loop scaling lost steps"
        # oscillation bound: exactly one grow and one shrink (+1 slack)
        assert len(decisions) <= 3, decisions
