"""Spark run() tests with an in-process stub of the pyspark barrier API
(pyspark is not installed here; the reference tests run on a local Spark,
``test/integration/test_spark.py`` — the stub checks the same contract:
barrier scheduling of num_proc tasks, allGather address exchange, launcher
env seeding, rank-ordered results, timeout cancellation)."""

import os
import sys
import threading
import time
import types

import pytest

import horovod_tpu.spark as hvd_spark


class _Comm:
    """allGather across the stub's task threads."""

    def __init__(self, n):
        self.barrier = threading.Barrier(n)
        self.msgs = [None] * n

    def all_gather(self, rank, msg):
        self.msgs[rank] = msg
        self.barrier.wait()
        out = list(self.msgs)
        self.barrier.wait()
        return out


class _StubBarrierContext:
    _local = threading.local()

    def __init__(self, rank, comm):
        self._rank = rank
        self._comm = comm

    @classmethod
    def get(cls):
        return cls._local.ctx

    def partitionId(self):
        return self._rank

    def allGather(self, msg):
        return self._comm.all_gather(self._rank, msg)


class _StubRDD:
    def __init__(self, n, hang=False):
        self.n = n
        self.hang = hang

    def barrier(self):
        return self

    def mapPartitions(self, task):
        self._task = task
        return self

    def collect(self):
        if self.hang:  # simulate tasks never getting scheduled
            threading.Event().wait(30)
            return []
        comm = _Comm(self.n)
        results = [None] * self.n
        errors = []

        def runner(rank):
            _StubBarrierContext._local.ctx = _StubBarrierContext(rank, comm)
            try:
                results[rank] = list(self._task(iter(())))
            except BaseException as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=runner, args=(r,), daemon=True)
                   for r in range(self.n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if errors:
            raise errors[0]
        out = []
        for part in results:
            out.extend(part or [])
        return out


class _StubSparkContext:
    def __init__(self, default_parallelism=2):
        self.defaultParallelism = default_parallelism
        self.cancelled = []
        self.hang_tasks = False

    def setJobGroup(self, group, desc, interruptOnCancel=False):
        self.group = group

    def cancelJobGroup(self, group):
        self.cancelled.append(group)

    def parallelize(self, data, n):
        return _StubRDD(n, hang=self.hang_tasks)


@pytest.fixture()
def stub_pyspark(monkeypatch):
    sc = _StubSparkContext()
    mod = types.ModuleType("pyspark")
    mod.SparkContext = types.SimpleNamespace(_active_spark_context=sc)
    mod.BarrierTaskContext = _StubBarrierContext
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    before = dict(os.environ)
    yield sc
    for k in [k for k in os.environ if k.startswith("HVD_")
              and k not in before]:
        del os.environ[k]


def test_spark_run_returns_rank_ordered_results(stub_pyspark):
    results = hvd_spark.run(lambda x: x * 2, args=(21,), num_proc=3)
    assert results == [42, 42, 42]


def test_spark_run_default_num_proc(stub_pyspark):
    results = hvd_spark.run(lambda: "ok")
    assert len(results) == stub_pyspark.defaultParallelism


def test_spark_run_seeds_launcher_env(stub_pyspark):
    envs = hvd_spark.run(
        lambda: {k: v for k, v in os.environ.items()
                 if k.startswith("HVD_") or k == "MY_FLAG"},
        num_proc=2, env={"MY_FLAG": "7"})
    for env in envs:
        assert env["HVD_SIZE"] == "2"
        assert env["HVD_NUM_PROCESSES"] == "2"
        assert env["HVD_KV_ADDR"]
        assert env["HVD_KV_PORT"]
        assert env["HVD_COORDINATOR_ADDR"]
        assert env["HVD_COORDINATOR_PORT"] != "0"
        assert env["HVD_SECRET_KEY"]
        assert env["MY_FLAG"] == "7"


def test_spark_run_propagates_worker_errors(stub_pyspark):
    def boom():
        raise ValueError("rank exploded")

    with pytest.raises(ValueError, match="rank exploded"):
        hvd_spark.run(boom, num_proc=2)


def test_spark_run_timeout_covers_startup_only(stub_pyspark):
    """start_timeout bounds task SCHEDULING, never training: a fn slower
    than the timeout still completes once every task registered."""
    results = hvd_spark.run(lambda: time.sleep(1.0) or "slow-ok",
                            num_proc=2, start_timeout=0.3)
    assert results == ["slow-ok", "slow-ok"]


def test_spark_run_timeout_cancels_unscheduled_job(stub_pyspark):
    stub_pyspark.hang_tasks = True  # tasks never start -> no registration
    with pytest.raises(TimeoutError, match="barrier"):
        hvd_spark.run(lambda: 1, num_proc=2, start_timeout=0.3)
    assert stub_pyspark.cancelled  # the spark job group was cancelled


def test_spark_run_requires_active_context(monkeypatch):
    mod = types.ModuleType("pyspark")
    mod.SparkContext = types.SimpleNamespace(_active_spark_context=None)
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    with pytest.raises(RuntimeError, match="SparkContext"):
        hvd_spark.run(lambda: 1, num_proc=1)


def test_module_imports_without_pyspark(monkeypatch):
    monkeypatch.setitem(sys.modules, "pyspark", None)
    # importing horovod_tpu.spark must not need pyspark; only run() does
    with pytest.raises(ImportError):
        hvd_spark.run(lambda: 1, num_proc=1)
