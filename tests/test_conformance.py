"""Lockstep conformance instrument (docs/conformance.md).

Covers both halves end to end: recorder determinism and the dump API
(``horovod_tpu/conformance.py``), the clean cross-rank diff at world=8,
the world=16 composite run (hierarchy auto-engaged + response cache +
QoS + step capture) diffing clean, BOTH planted divergence demos found
and localized to the first divergent event with site + rank pair, the
hvdtrace binary-search localization and digest fast path on synthetic
traces, and the protocol FSM fixtures.

The planted demos deadlock for REAL — a divergent flush composition is
a negotiation that never completes — so they run bounded
(``HVD_ELASTIC_TIMEOUT=8`` + stall checker off + ``allow_failures``):
every rank fails with the collective error in seconds and the abort
path still dumps each rank's trace, which is exactly the production
flow the instrument exists for.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import _native
from horovod_tpu import conformance

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools import hvdtrace  # noqa: E402

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")

FLUSH_SITE = "ops/fusion_cycle.py::FusionScheduler.flush_queue"

# pinned cycle knobs: every flush comes from an explicit cut, the
# comparability precondition (docs/conformance.md "What the flush hash
# covers")
PINNED = {"HVD_CYCLE_TIME": "500", "HVD_PENDING_CYCLE_TIME": "500"}

# a planted divergence hangs negotiation until the exchange deadline;
# bound it so the demo fails (and dumps) in seconds instead of 600 s
DEMO_BOUND = {"HVD_ELASTIC_TIMEOUT": "8", "HVD_STALL_CHECK_DISABLE": "1"}


@pytest.fixture(autouse=True)
def _restore_gate():
    """Worlds enable the process-global gate via their env overlays;
    re-read it from the (unset) main-thread env afterwards so recording
    never leaks into unrelated tests."""
    yield
    conformance.set_enabled(None)
    conformance.refresh()
    conformance.reset()


# ---------------------------------------------------------------------------
# recorder unit behavior
# ---------------------------------------------------------------------------


class TestRecorderDeterminism:
    EVENTS = [
        (FLUSH_SITE, "flush", ("allreduce", ("g0", "g1", "g2"))),
        ("qos.py::QosGate._grant_locked", "grant", ("serve", 1, False)),
        (FLUSH_SITE, "flush", ("allgather", ("h0",))),
        ("negotiation/response_cache.py::ResponseCache.note_response",
         "confirm", ("global", "g0")),
    ]

    def test_identical_streams_make_identical_chains(self):
        a, b = conformance.Recorder(), conformance.Recorder()
        for site, kind, payload in self.EVENTS * 5:
            a.note(site, kind, payload)
            b.note(site, kind, payload)
        assert a.chains == b.chains
        assert [e[5] for e in a.events] == [e[5] for e in b.events]
        assert a.chains["flush"] != 0 and a.chains["qos"] != 0

    def test_one_payload_difference_diverges_the_stream_chain(self):
        a, b = conformance.Recorder(), conformance.Recorder()
        for site, kind, payload in self.EVENTS:
            a.note(site, kind, payload)
            b.note(site, kind, payload)
        a.note(FLUSH_SITE, "flush", ("allreduce", ("x0", "x1")))
        b.note(FLUSH_SITE, "flush", ("allreduce", ("x0",)))
        assert a.chains["flush"] != b.chains["flush"]
        # the other streams are untouched: streams isolate divergence
        assert a.chains["qos"] == b.chains["qos"]
        assert a.chains["rcache"] == b.chains["rcache"]

    def test_local_events_never_chain(self):
        rec = conformance.Recorder()
        rec.note("ops/dispatch_cache.py::store", "plan_store",
                 ("eager", 12345))
        rec.note("engine_service.py::DynamicService.__init__",
                 "svc_start", ("global", 4, 0))
        assert rec.chains["plans"] == 0
        assert rec.chains["service"] == 0
        # but the events carry their own content crc and land in the ring
        assert all(e[5] != 0 for e in rec.events)
        assert len(rec.ring) == 2

    def test_ring_is_bounded_events_are_not(self, monkeypatch):
        monkeypatch.setenv("HVD_CONFORMANCE_RING", "4")
        rec = conformance.Recorder()
        for i in range(10):
            rec.note(FLUSH_SITE, "flush", ("allreduce", (f"t{i}",)))
        assert len(rec.events) == 10
        assert len(rec.ring) == 4
        assert rec.ring[0][0] == 6  # oldest retained seq: truncation marker

    def test_disabled_record_is_a_noop(self):
        conformance.reset()
        conformance.set_enabled(False)
        conformance.record(FLUSH_SITE, "flush", ("allreduce", ("a",)))
        assert conformance.conformance_stats()["events"] == 0

    def test_dump_roundtrips_through_json(self, tmp_path):
        conformance.reset()
        conformance.set_enabled(True)
        conformance.record(FLUSH_SITE, "flush", ("allreduce", ("a", "b")))
        target = tmp_path / "trace.json"
        doc = conformance.conformance_dump(str(target))
        loaded = json.loads(target.read_text())
        assert loaded["schema"] == conformance.TRACE_SCHEMA
        assert loaded["chains"] == doc["chains"]
        assert any(e[3] == FLUSH_SITE for e in loaded["events"])
        # no dir knob + no explicit path -> snapshot only, no write
        assert "path" not in conformance.conformance_dump()


# ---------------------------------------------------------------------------
# differ unit behavior (synthetic traces; no world)
# ---------------------------------------------------------------------------


def _rank_doc(rank: int, feed) -> dict:
    """A trace document from a real Recorder fed ``feed``, re-labeled as
    ``rank``."""
    rec = conformance.Recorder()
    for site, kind, payload in feed:
        rec.note(site, kind, payload)
    doc = rec.trace()
    doc.update({"label": f"rank{rank}", "rank": rank, "size": 2,
                "world": "synth", "round": "1"})
    return doc


def _write_docs(tmp_path, docs):
    for doc in docs:
        name = f"hvdtrace-synth-r1-g0-rank{doc['rank']}.json"
        (tmp_path / name).write_text(json.dumps(doc))


class TestDifferLocalization:
    def test_digest_fast_path_identical_traces_clean(self, tmp_path):
        feed = [(FLUSH_SITE, "flush", ("allreduce", (f"t{i}",)))
                for i in range(8)]
        _write_docs(tmp_path, [_rank_doc(0, feed), _rank_doc(1, feed)])
        findings, errors, summary = hvdtrace.run_check([str(tmp_path)])
        assert findings == [] and errors == []
        assert summary["traces"] == 2 and summary["divergences"] == 0

    def test_binary_search_finds_first_divergent_index(self, tmp_path):
        common = [(FLUSH_SITE, "flush", ("allreduce", (f"t{i}",)))
                  for i in range(11)]
        a = common + [(FLUSH_SITE, "flush", ("allreduce", ("same",)))] * 9
        b = (common
             + [(FLUSH_SITE, "flush", ("allreduce", ("DIVERGED",)))]
             + [(FLUSH_SITE, "flush", ("allreduce", ("same",)))] * 8)
        _write_docs(tmp_path, [_rank_doc(0, a), _rank_doc(1, b)])
        findings, _errors, summary = hvdtrace.run_check([str(tmp_path)])
        divs = [f for f in findings if f["type"] == "divergence"]
        assert len(divs) == 1 and summary["divergences"] == 1
        f0 = divs[0]
        # the FIRST divergent event, not just "the streams differ":
        # index 11 is the mid-stream cut, with both payloads quoted
        assert f0["stream"] == "flush" and f0["index"] == 11
        assert f0["rank_a"] == "rank0" and f0["rank_b"] == "rank1"
        assert f0["a"]["site"] == FLUSH_SITE
        assert "same" in f0["a"]["payload"]
        assert "DIVERGED" in f0["b"]["payload"]
        # the report names site, rank pair, and both payloads
        text = hvdtrace.format_finding(f0)
        assert "DIVERGENCE" in text and FLUSH_SITE in text
        assert "rank0" in text and "rank1" in text

    def test_length_skew_localizes_past_shared_prefix(self, tmp_path):
        common = [(FLUSH_SITE, "flush", ("allreduce", (f"t{i}",)))
                  for i in range(5)]
        _write_docs(tmp_path, [_rank_doc(0, common),
                               _rank_doc(1, common[:3])])
        findings, _errors, _summary = hvdtrace.run_check([str(tmp_path)])
        divs = [f for f in findings if f["type"] == "divergence"]
        assert len(divs) == 1
        assert divs[0]["index"] == 3  # shared prefix matched in full
        assert divs[0]["a"] is not None and divs[0]["b"] is None

    def test_missing_rank_is_an_incomplete_group(self, tmp_path):
        feed = [(FLUSH_SITE, "flush", ("allreduce", ("t",)))]
        doc = _rank_doc(0, feed)
        doc["size"] = 4
        _write_docs(tmp_path, [doc])
        findings, _errors, summary = hvdtrace.run_check([str(tmp_path)])
        assert summary["incomplete_groups"] == 1
        assert findings[0]["type"] == "missing-ranks"
        assert findings[0]["missing"] == 3

    def test_cli_json_exit_codes(self, tmp_path):
        clean, bad = tmp_path / "clean", tmp_path / "bad"
        clean.mkdir(), bad.mkdir()
        feed = [(FLUSH_SITE, "flush", ("allreduce", ("t",)))]
        _write_docs(clean, [_rank_doc(0, feed), _rank_doc(1, feed)])
        _write_docs(bad, [
            _rank_doc(0, feed),
            _rank_doc(1, [(FLUSH_SITE, "flush", ("allreduce", ("x",)))])])

        def cli(*args):
            env = dict(os.environ)
            env["PYTHONPATH"] = (str(REPO_ROOT) + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            return subprocess.run(
                [sys.executable, "-m", "tools.hvdtrace", *args],
                capture_output=True, text=True, env=env, cwd=REPO_ROOT,
                timeout=60)

        ok = cli(str(clean), "--json")
        assert ok.returncode == 0, ok.stderr
        assert json.loads(ok.stdout)["clean"] is True
        div = cli(str(bad), "--json")
        assert div.returncode == 1, div.stderr
        report = json.loads(div.stdout)
        assert report["summary"]["divergences"] == 1
        empty = cli(str(tmp_path / "nowhere"))
        assert empty.returncode == 2


# ---------------------------------------------------------------------------
# protocol FSM fixtures
# ---------------------------------------------------------------------------


def _fsm_doc(ring) -> dict:
    rows = [[seq, site, kind, repr(payload)]
            for seq, (site, kind, payload) in enumerate(ring)]
    return {"schema": 1, "label": "rank0", "rank": 0, "size": 1,
            "world": "fsm", "round": "0", "generation": 0,
            "chains": {}, "events": [], "ring": rows,
            "n_events": len(rows)}


CAP = "ops/step_capture.py::CaptureState"
RC = "negotiation/response_cache.py::ResponseCache"
SVC = "engine_service.py::DynamicService"
EPOCH = "conformance.py::Recorder.note"


class TestProtocolFsm:
    def _rules(self, ring):
        return [f["rule"] for f in hvdtrace.validate_fsm(_fsm_doc(ring))]

    def test_seal_outside_record_is_illegal(self):
        ring = [(f"{CAP}.boundary", "phase", ("idle", "replay")),
                (f"{CAP}._seal_locked", "seal", (3, 123))]
        assert self._rules(ring) == ["capture-seal"]
        ring = [(f"{CAP}.boundary", "phase", ("idle", "record")),
                (f"{CAP}._seal_locked", "seal", (3, 123))]
        assert self._rules(ring) == []

    def test_explicit_transition_into_replayed_is_illegal(self):
        ring = [(f"{CAP}.boundary", "phase", ("replay", "replayed"))]
        assert self._rules(ring) == ["capture-phase"]

    def test_phase_from_must_chain(self):
        ring = [(f"{CAP}.boundary", "phase", ("idle", "record")),
                (f"{CAP}.boundary", "phase", ("replay", "idle"))]
        assert self._rules(ring) == ["capture-phase"]

    def test_replay_completion_only_from_replay(self):
        ring = [(f"{CAP}.boundary", "phase", ("idle", "record")),
                (f"{CAP}._execute_replay", "replayed", (4,))]
        assert self._rules(ring) == ["capture-replay"]
        ring = [(f"{CAP}.boundary", "phase", ("idle", "replay")),
                (f"{CAP}._execute_replay", "replayed", (4,))]
        assert self._rules(ring) == []

    def test_warm_confirm_needs_nonempty_restore(self):
        ring = [(f"{RC}.confirm_warm", "warm_confirm", ("global", 3))]
        assert self._rules(ring) == ["warm-order"]
        ring = [(f"{RC}.restore_warm", "warm_restore", ("global", 5)),
                (f"{RC}.confirm_warm", "warm_confirm", ("global", 3))]
        assert self._rules(ring) == []
        # empty confirms are legal anytime (drop_warm fires at n==0 too)
        ring = [(f"{RC}.confirm_warm", "warm_confirm", ("global", 0)),
                (f"{RC}.drop_warm", "warm_drop", ("global", 0))]
        assert self._rules(ring) == []

    def test_served_after_join_is_illegal(self):
        ring = [(f"{SVC}.__init__", "svc_start", ("global", 2, 0)),
                (f"{SVC}.join", "join", ("global", "jn")),
                (f"{RC}.count_served", "served", ("global", 2, 1))]
        assert self._rules(ring) == ["served-after-join"]

    def test_join_after_abort_is_illegal(self):
        ring = [(f"{SVC}.__init__", "svc_start", ("global", 2, 0)),
                (f"{SVC}._on_peer_failure", "svc_abort", ("global", 1)),
                (f"{SVC}.join", "join", ("global", "jn"))]
        assert self._rules(ring) == ["service-lifecycle"]

    def test_service_events_need_svc_start_unless_truncated(self):
        ring = [(f"{SVC}.stop", "svc_stop", ("global",))]
        assert self._rules(ring) == ["service-lifecycle"]
        # a ring that no longer covers the trace head suppresses
        # "must be preceded by" rules for the unseen prefix
        doc = _fsm_doc(ring)
        doc["ring"][0][0] = 7  # first retained seq > 0: truncated
        assert hvdtrace.validate_fsm(doc) == []

    def test_epoch_moves_chain_and_stay_monotone(self):
        ring = [(EPOCH, "epoch", (0, 1)), (EPOCH, "epoch", (5, 7))]
        assert self._rules(ring) == ["epoch-chain"]
        ring = [(EPOCH, "epoch", (3, 2))]
        assert self._rules(ring) == ["epoch-chain"]
        ring = [(EPOCH, "epoch", (0, 1)), (EPOCH, "epoch", (1, 4))]
        assert self._rules(ring) == []


# ---------------------------------------------------------------------------
# clean worlds diff clean
# ---------------------------------------------------------------------------


class TestCleanWorldDiff:
    def test_world8_clean_cross_rank_diff(self, tmp_path):
        extra = {**PINNED, "HVD_CONFORMANCE": "1",
                 "HVD_CONFORMANCE_DIR": str(tmp_path)}
        with hvd.loopback.world(8, extra_env=extra) as w:
            def body():
                r = hvd.rank()
                for i in range(3):
                    out = hvd.allreduce(jnp.full((4,), float(r + i)),
                                        op=hvd.Sum, name=f"e{i}")
                    np.asarray(out)
                hs = [hvd.allreduce_async(jnp.full((8,), float(r + i)),
                                          op=hvd.Sum, name=f"a{i}")
                      for i in range(6)]
                hvd.fusion_flush()
                vals = [np.asarray(h.result()) for h in hs]
                assert all(v.shape == (8,) for v in vals)
                return "OK"

            outs = w.run(body, timeout=240)
            assert [o.result for o in outs] == ["OK"] * 8

        findings, errors, summary = hvdtrace.run_check([str(tmp_path)])
        assert errors == []
        assert summary["traces"] == 8
        assert len(summary["groups"]) == 1
        assert summary["groups"][0]["ranks"] == [f"rank{r}"
                                                 for r in range(8)]
        assert findings == [], [hvdtrace.format_finding(f)
                                for f in findings]


_COMPOSITE_SCRIPT = r"""
import os
import threading
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.utils import envs

N = 16
# HVD_QOS deliberately NOT seeded: the runtime keeps step capture and
# QoS mutually exclusive (envs.step_capture_enabled), so the composite
# phases them — capture segment first, then a mid-run knob override
# turns QoS on, which also exercises the override-epoch stream
extra = {
    "HVD_CONFORMANCE": "1",
    "HVD_CONFORMANCE_DIR": os.environ["CONF_DIR"],
    "HVD_CYCLE_TIME": "500",
    "HVD_PENDING_CYCLE_TIME": "500",
    "HVD_RESPONSE_CACHE": "1",
    "HVD_STEP_CAPTURE": "1",
}

_flip_mu = threading.Lock()

def flip_qos_on():
    # serialized across rank threads: set_override's no-op guard is
    # check-then-act, and 16 racing callers would bump the epoch twice
    # (ranks would then disagree on the (old, new) moves they record)
    with _flip_mu:
        envs.set_override(envs.QOS, "1")

with hvd.loopback.world(N, extra_env=extra) as w:
    def body():
        r = hvd.rank()
        # capture segment: one recorded step, two replayed
        for step in range(3):
            hvd.step_marker()
            hs = [hvd.allreduce_async(
                      jnp.full((4,), float(r + i + step)), op=hvd.Sum,
                      name=f"t{i}") for i in range(3)]
            [np.asarray(h.result()) for h in hs]
        hvd.step_marker()
        # rendezvous AFTER the final marker: its completed result means
        # every rank has passed its last capture boundary, so the flip
        # below cannot race a straggler's enabled() read mid-boundary
        # (the boundary's phase move depends on the live QoS knob)
        np.asarray(hvd.allreduce(jnp.full((2,), float(r)), op=hvd.Sum,
                                 name="pre_flip_barrier"))
        flip_qos_on()
        # steady eager segment: repeated identical rounds arm and then
        # serve the response cache; dispatch plans on the cold calls
        for i in range(5):
            np.asarray(hvd.allreduce(jnp.full((4,), float(r)),
                                     op=hvd.Sum, name="steady"))
        # explicit-cut flush segment under QoS admission
        hs = [hvd.allreduce_async(jnp.full((8,), float(r + i)),
                                  op=hvd.Sum, name=f"q{i}")
              for i in range(4)]
        hvd.fusion_flush()
        [np.asarray(h.result()) for h in hs]
        return "OK"

    outs = w.run(body, timeout=600)
    bad = [o.error for o in outs if o.result != "OK"]
    assert not bad, bad
print("COMPOSITE_OK")
"""


class TestCompositeWorld16:
    def test_composite_world16_diffs_clean(self, tmp_path):
        """The acceptance run: world=16 (hierarchical control plane
        auto-engaged) with response cache + QoS + step capture all on,
        conformance recording — zero divergences, zero FSM violations,
        and every subsystem's stream actually populated."""
        env = dict(os.environ)
        env.pop("HVD_FAULT_SPEC", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
        env["PYTHONPATH"] = (str(REPO_ROOT) + os.pathsep
                             + env.get("PYTHONPATH", ""))
        env["CONF_DIR"] = str(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-c", _COMPOSITE_SCRIPT], cwd=REPO_ROOT,
            env=env, capture_output=True, text=True, timeout=900)
        assert proc.returncode == 0 and "COMPOSITE_OK" in proc.stdout, (
            f"stdout:\n{proc.stdout[-3000:]}\nstderr:"
            f"\n{proc.stderr[-4000:]}")

        findings, errors, summary = hvdtrace.run_check([str(tmp_path)])
        assert errors == []
        assert summary["traces"] == 16
        assert summary["divergences"] == 0, \
            [hvdtrace.format_finding(f) for f in findings]
        assert summary["fsm_violations"] == 0, \
            [hvdtrace.format_finding(f) for f in findings]
        assert summary["incomplete_groups"] == 0
        # the composite actually exercised the subsystems it claims to:
        # every conformance stream (including the QoS-flip epoch move)
        # is live in the traces
        docs, _ = hvdtrace.load_traces([str(tmp_path)])
        streams = {e[1] for d in docs for e in d["events"]}
        for required in ("flush", "capture", "rcache", "plans", "qos",
                         "service", "epoch"):
            assert required in streams, streams


# ---------------------------------------------------------------------------
# the two planted divergence demos
# ---------------------------------------------------------------------------


class TestPlantedDivergences:
    def test_knob_skew_found_and_localized(self, tmp_path):
        """Demo (a): one rank runs with a skewed HVD_FUSION_THRESHOLD —
        its flushes split where everyone else coalesces. Without the
        instrument this is the generic exchange-deadline hang; with it,
        the differ names the flush site and the odd rank out."""
        n = 4
        base = {**PINNED, **DEMO_BOUND, "HVD_CONFORMANCE": "1",
                "HVD_CONFORMANCE_DIR": str(tmp_path)}

        def body():
            r = hvd.rank()
            hs = [hvd.allreduce_async(jnp.full((1024,), float(r + i)),
                                      op=hvd.Sum, name=f"s{i}")
                  for i in range(6)]
            hvd.fusion_flush()
            [np.asarray(h.result()) for h in hs]
            return "OK"

        w = hvd.loopback.LoopbackWorld(n, name="skew")
        try:
            handles = []
            for r in range(n):
                extra = dict(base)
                if r == 1:
                    extra["HVD_FUSION_THRESHOLD"] = "1024"
                handles.append(w.spawn(body, w.rank_env(r, n, extra=extra),
                                       auto_init=True))
            for h in handles:
                h.wait()
            # the skew deadlocks negotiation; the bounded deadline fails
            # the ranks instead of hanging for 600 s
            assert any(h.outcome.error is not None for h in handles)
        finally:
            w.shutdown()

        findings, _errors, summary = hvdtrace.run_check([str(tmp_path)])
        assert summary["traces"] == n
        divs = [f for f in findings if f["type"] == "divergence"
                and f["stream"] == "flush"]
        # rank 1 is the only divergent rank: exactly the rank0-vs-rank1
        # comparison trips, localized to the FIRST flush event
        assert len(divs) == 1, [hvdtrace.format_finding(f)
                                for f in findings]
        f0 = divs[0]
        assert (f0["rank_a"], f0["rank_b"]) == ("rank0", "rank1")
        assert f0["index"] == 0
        assert f0["a"]["site"] == FLUSH_SITE
        # both compositions quoted: 6 coalesced names vs the split flush
        assert "s5" in f0["a"]["payload"]
        assert "s5" not in f0["b"]["payload"]

    def test_rank_conditioned_flush_found_and_localized(self, tmp_path):
        """Demo (b): rank 0 cuts its queue mid-stream with a
        rank-conditioned ``fusion_flush()`` — the canonical
        rank-divergent control flow bug (hvdlint pass 7's dynamic
        twin)."""
        n = 4
        extra = {**PINNED, **DEMO_BOUND, "HVD_CONFORMANCE": "1",
                 "HVD_CONFORMANCE_DIR": str(tmp_path)}
        with hvd.loopback.world(n, extra_env=extra) as w:
            def body():
                r = hvd.rank()
                hs = [hvd.allreduce_async(jnp.full((4,), float(r + i)),
                                          op=hvd.Sum, name=f"c{i}")
                      for i in range(3)]
                if r == 0:
                    hvd.fusion_flush()  # the planted bug
                hs += [hvd.allreduce_async(jnp.full((4,), float(r + i)),
                                           op=hvd.Sum, name=f"c{3 + i}")
                       for i in range(3)]
                hvd.fusion_flush()
                [np.asarray(h.result()) for h in hs]
                return "OK"

            outs = w.run(body, timeout=120, allow_failures=True)
            assert any(o.error is not None for o in outs)

        findings, _errors, summary = hvdtrace.run_check([str(tmp_path)])
        assert summary["traces"] == n
        divs = [f for f in findings if f["type"] == "divergence"
                and f["stream"] == "flush"]
        # rank 0 (the reference) diverges from every other rank
        assert len(divs) == n - 1, [hvdtrace.format_finding(f)
                                    for f in findings]
        for f0 in divs:
            assert f0["rank_a"] == "rank0"
            assert f0["index"] == 0
            assert f0["a"]["site"] == FLUSH_SITE
            # rank 0's first flush carries only the early cut's tensors
            assert "c5" not in f0["a"]["payload"]
            assert "c5" in f0["b"]["payload"]
