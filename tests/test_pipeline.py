"""Pipeline parallelism: the GPipe microbatch schedule over a mesh axis
must match applying the stages sequentially on one device — forward and
gradients — and compose with data parallelism on a second axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import (
    microbatch,
    pipeline_apply,
    stack_stage_params,
    unstack_stage,
)

DIM = 8


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{"w": jnp.asarray(rng.standard_normal((DIM, DIM)) / np.sqrt(DIM),
                              jnp.float32),
             "b": jnp.asarray(rng.standard_normal(DIM) * 0.1, jnp.float32)}
            for _ in range(n)]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_microbatch_validates():
    with pytest.raises(ValueError, match="divide"):
        microbatch(jnp.zeros((10, 2)), 4)
    assert microbatch(jnp.zeros((8, 2)), 4).shape == (4, 2, 2)


@pytest.mark.parametrize("n_micro", [8, 12])
def test_pipeline_matches_sequential(n_micro):
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    stages = _make_stages(n)
    x = np.random.default_rng(1).standard_normal((24, DIM)).astype(
        np.float32)
    stacked = stack_stage_params(stages)

    fn = jax.jit(jax.shard_map(
        lambda p, x: pipeline_apply(_stage_fn, unstack_stage(p), x, axis,
                                    n_microbatches=n_micro),
        mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
        check_vma=False))
    out = np.asarray(fn(
        jax.device_put(stacked, NamedSharding(mesh, P(axis))),
        jnp.asarray(x)))
    expect = np.asarray(_sequential(stages, jnp.asarray(x)))
    assert np.allclose(out, expect, rtol=1e-5, atol=1e-6), \
        np.abs(out - expect).max()


@pytest.mark.parametrize("remat", [False, True])
def test_pipeline_gradients_match(remat):
    """d(loss)/d(stage params) through the schedule (ppermute transposes +
    scan reverse sweep) equals sequential-composition gradients, with and
    without stage rematerialization."""
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    stages = _make_stages(n, seed=2)
    x = np.random.default_rng(3).standard_normal((16, DIM)).astype(
        np.float32)
    tgt = np.random.default_rng(4).standard_normal((16, DIM)).astype(
        np.float32)
    stacked = stack_stage_params(stages)

    def pipe_loss(p, x):
        out = pipeline_apply(_stage_fn, unstack_stage(p), x, axis,
                             n_microbatches=8, remat=remat)
        return jnp.mean((out - tgt) ** 2)

    grad_fn = jax.jit(jax.shard_map(
        jax.grad(pipe_loss), mesh=mesh, in_specs=(P(axis), P()),
        out_specs=P(axis), check_vma=False))
    g = grad_fn(jax.device_put(stacked, NamedSharding(mesh, P(axis))),
                jnp.asarray(x))

    def seq_loss(stages, x):
        return jnp.mean((_sequential(stages, x) - tgt) ** 2)

    eg = jax.grad(seq_loss)(stages, jnp.asarray(x))
    eg_stacked = stack_stage_params(eg)
    for k in ("w", "b"):
        got, want = np.asarray(g[k]), np.asarray(eg_stacked[k])
        assert np.allclose(got, want, rtol=1e-4, atol=1e-6), \
            (k, np.abs(got - want).max())


def test_pipeline_composes_with_data_parallel():
    """dp x pp mesh: batch sharded over dp, stages over pp; gradients
    pmean over dp — one training step must move the loss."""
    import optax

    n = hvd.size()
    if n % 2:
        pytest.skip("needs even device count")
    pp, dp = 2, n // 2
    devs = np.array(jax.devices()[:n]).reshape(dp, pp)
    mesh = Mesh(devs, ("dp", "pp"))
    stages = _make_stages(pp, seed=5)
    stacked = stack_stage_params(stages)
    rng = np.random.default_rng(6)
    x = rng.standard_normal((8 * dp, DIM)).astype(np.float32)
    y = rng.standard_normal((8 * dp, DIM)).astype(np.float32)
    tx = optax.sgd(0.2)
    opt_state = tx.init(stacked)

    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            out = pipeline_apply(_stage_fn, unstack_stage(p), x, "pp",
                                 n_microbatches=4)
            return jnp.mean((out - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        loss = jax.lax.pmean(loss, "dp")
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P("pp"), P("pp"), P("dp"), P("dp")),
        out_specs=(P("pp"), P("pp"), P()), check_vma=False))
    params = jax.device_put(stacked, NamedSharding(mesh, P("pp")))
    opt_state = jax.device_put(opt_state, NamedSharding(mesh, P("pp")))
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
    ys = jax.device_put(y, NamedSharding(mesh, P("dp")))
    l0 = None
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, xs, ys)
        loss = float(jax.block_until_ready(loss))
        l0 = l0 if l0 is not None else loss
    assert loss < l0, (l0, loss)


def test_pipeline_input_gradients_replicated_and_exact():
    """d(loss)/dx must be the full sequential-composition input gradient,
    identical on EVERY pp rank (the _replicated_input VJP) — shared
    layers upstream of the pipeline train correctly with or without a
    pmean over pp."""
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    stages = _make_stages(n, seed=7)
    x = np.random.default_rng(8).standard_normal((8, DIM)).astype(
        np.float32)
    tgt = np.random.default_rng(9).standard_normal((8, DIM)).astype(
        np.float32)
    stacked = stack_stage_params(stages)

    def pipe_loss(p, x):
        out = pipeline_apply(_stage_fn, unstack_stage(p), x, axis,
                             n_microbatches=4)
        return jnp.mean((out - tgt) ** 2)

    # out_specs P(axis) exposes every rank's dx copy for inspection
    gx_fn = jax.jit(jax.shard_map(
        lambda p, x: jax.grad(pipe_loss, argnums=1)(p, x)[None],
        mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis),
        check_vma=False))
    per_rank = np.asarray(gx_fn(
        jax.device_put(stacked, NamedSharding(mesh, P(axis))),
        jnp.asarray(x)))
    assert per_rank.shape == (n, 8, DIM)

    def seq_loss(x):
        return jnp.mean((_sequential(stages, x) - tgt) ** 2)

    expect = np.asarray(jax.grad(seq_loss)(jnp.asarray(x)))
    for r in range(n):  # identical AND exact on every rank
        assert np.allclose(per_rank[r], expect, rtol=1e-4, atol=1e-7), r
