"""Real-Ray integration tests, skipped when Ray is not installed (the
reference runs the same shape against local Ray,
``/root/reference/test/single/test_ray.py``). The stub tests in
test_ray.py / test_ray_elastic.py cover the contract in stub form;
these catch the actor-lifecycle/placement behavior stubs cannot."""

import os

import pytest

ray = pytest.importorskip("ray")

from horovod_tpu.ray import ElasticRayExecutor, RayExecutor, RayHostDiscovery


@pytest.fixture(scope="module")
def ray_cluster():
    if not ray.is_initialized():
        ray.init(num_cpus=4, include_dashboard=False,
                 ignore_reinit_error=True)
    yield
    ray.shutdown()


def _worker_env():
    return {k: v for k, v in os.environ.items() if k.startswith("HVD_")}


def test_real_ray_executor_runs_and_seeds_env(ray_cluster):
    ex = RayExecutor(num_workers=2)
    ex.start()
    try:
        envs = ex.run(_worker_env)
        assert len(envs) == 2
        ranks = sorted(int(e["HVD_RANK"]) for e in envs)
        assert ranks == [0, 1]
        for e in envs:
            assert e["HVD_SIZE"] == "2"
            assert e["HVD_KV_ADDR"] and e["HVD_KV_PORT"]
            assert e["HVD_SECRET_KEY"]
        assert ex.execute_single(lambda: "r0") == "r0"
    finally:
        ex.shutdown()


def test_real_ray_host_discovery_sees_cluster(ray_cluster):
    disc = RayHostDiscovery(ray, cpus_per_worker=1)
    hosts = disc.find_available_hosts_and_slots()
    assert hosts, "no hosts discovered from live cluster state"
    assert sum(hosts.values()) >= 4  # the num_cpus=4 local node


def test_real_elastic_ray_completes(ray_cluster):
    """Happy-path elastic run on a static local cluster: workers register
    ready/done through the KV and the driver declares success."""
    from horovod_tpu.elastic.driver import done_key, ready_key
    from horovod_tpu.runner.http_kv import KVClient

    def worker(*args):
        env = {k: v for k, v in os.environ.items()}
        kv = KVClient(env["HVD_KV_ADDR"], int(env["HVD_KV_PORT"]),
                      secret=env["HVD_SECRET_KEY"])
        host = env["HVD_HOSTNAME"]
        slot = int(env["HVD_LOCAL_RANK"])
        rnd = int(env["HVD_ELASTIC_ROUND"])
        kv.put(ready_key(rnd, host, slot), b"1")
        kv.put(done_key(host, slot), b"1")
        return f"{host}/{slot}"

    ex = ElasticRayExecutor(min_workers=2, elastic_timeout=60)
    ex.start()
    try:
        results = ex.run(worker)
    finally:
        ex.shutdown()
    assert len(results) == 2
