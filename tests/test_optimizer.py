"""DistributedOptimizer / gradient-tape tests (reference analog:
``test/parallel/test_torch.py`` optimizer tests and
``test_tensorflow2_keras.py`` aggregation tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

N = 8


def test_distributed_optimizer_traced_sgd(hvd):
    """SPMD data-parallel step: per-rank grads differ; after the wrapped
    update every rank applies the *mean* gradient."""
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    params = {"w": jnp.zeros((3,))}
    state = jax.eval_shape(lambda: None)  # placeholder
    x = jnp.arange(1.0, 9.0).reshape(N, 1)

    def step(xi):
        grads = {"w": jnp.full((3,), xi[0])}
        st = tx.init(params)
        updates, _ = tx.update(grads, st, params)
        return optax.apply_updates(params, updates)["w"]

    out = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    got = np.asarray(out).reshape(N, 3)
    np.testing.assert_allclose(got, np.full((N, 3), -4.5), rtol=1e-6)


def test_value_and_grad_traced(hvd):
    def loss(w, xi):
        return jnp.sum(w * xi)

    vg = hvd.value_and_grad(loss, op=hvd.Average)
    x = jnp.arange(1.0, 9.0).reshape(N, 1)

    def step(xi):
        _, g = vg(jnp.ones((1,)), xi)
        return g

    out = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.full(N, 4.5))


def test_grad_wrapper(hvd):
    g = hvd.grad(lambda w: jnp.sum(w ** 2))
    out = g(jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 2.0))


def test_compression_fp16(hvd):
    tensor = jnp.full((4,), 3.0)
    c, ctx = hvd.Compression.fp16.compress(tensor)
    assert c.dtype == jnp.float16
    d = hvd.Compression.fp16.decompress(c, ctx)
    assert d.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(d), 3.0)


def test_compression_bf16_in_tape(hvd):
    vg = hvd.value_and_grad(lambda w: jnp.sum(w * 2), compression=hvd.Compression.bf16)
    _, g = vg(jnp.ones((4,)))
    assert g.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(g), 2.0)


def test_backward_passes_per_step(hvd):
    tx = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    params = {"w": jnp.zeros((2,))}
    st = tx.init(params)
    g1 = {"w": jnp.full((2,), 1.0)}
    g2 = {"w": jnp.full((2,), 3.0)}
    u1, st = tx.update(g1, st, params)
    # first of 2 passes: no update applied yet
    np.testing.assert_allclose(np.asarray(u1["w"]), 0.0)
    u2, st = tx.update(g2, st, params)
    # second pass: mean grad (1+3)/2 = 2 -> update -2
    np.testing.assert_allclose(np.asarray(u2["w"]), -2.0)


def test_broadcast_parameters(hvd):
    params = {"a": jnp.ones((3,)), "b": {"c": jnp.zeros((2, 2))}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["a"]), 1.0)
    np.testing.assert_allclose(np.asarray(out["b"]["c"]), 0.0)


def test_broadcast_optimizer_state(hvd):
    tx = optax.adam(1e-3)
    st = tx.init({"w": jnp.ones((3,))})
    out = hvd.broadcast_optimizer_state(st, root_rank=0)
    chex_leaves = jax.tree.leaves(out)
    assert len(chex_leaves) == len(jax.tree.leaves(st))


def test_broadcast_object(hvd):
    obj = {"epoch": 3, "name": "resnet"}
    assert hvd.broadcast_object(obj, 0) == obj


def test_allgather_object(hvd):
    assert hvd.allgather_object({"r": 1}) == [{"r": 1}]


def test_adasum_eager_two_orthogonal(hvd):
    """Orthogonal gradients should (nearly) add; parallel identical
    gradients should average to the same vector (scale invariance) —
    numerics per adasum.h:248-342."""
    ps = hvd.add_process_set([0, 1])
    a = jnp.array([1.0, 0.0])
    b = jnp.array([0.0, 1.0])
    out = hvd.allreduce(hvd.per_rank([a, b], ps), op=hvd.Adasum, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), [1.0, 1.0], atol=1e-6)
    hvd.remove_process_set(ps)


def test_adasum_identical_gradients(hvd):
    """n identical gradients g: pairwise combine gives (1-1/2)g+(1-1/2)g = g,
    so the result stays g at every level."""
    g = jnp.array([2.0, -1.0, 0.5])
    out = hvd.allreduce(hvd.per_rank([g] * 8), op=hvd.Adasum)
    np.testing.assert_allclose(np.asarray(out), np.asarray(g), rtol=1e-6)


def test_grad_has_aux(hvd):
    def loss(w):
        return jnp.sum(w ** 2), {"n": w.shape[0]}

    grads, aux = hvd.grad(loss, has_aux=True)(jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(grads), 2.0)
    assert aux == {"n": 3}
