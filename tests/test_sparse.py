"""Sparse (indexed-rows) gradient path tests — the analog of the
reference's IndexedSlices allreduce coverage in
``test/parallel/test_tensorflow.py`` (sparse allreduce = values+indices
allgather, ``tensorflow/__init__.py:95-112``)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.sparse import (
    SparseRows,
    rows_from_dense,
    rows_to_dense,
    sparse_allreduce,
    sparse_allreduce_to_dense,
)
from horovod_tpu.utils import envs

VOCAB, DIM = 32, 4


def dense_grad_for_rank(r, n):
    """Rank r touches rows {r, r+1, n+5} with known values."""
    g = np.zeros((VOCAB, DIM), np.float32)
    g[r] = r + 1.0
    g[r + 1] += 2.0
    g[n + 5] += 10.0 + r
    return g


def test_rows_round_trip():
    g = dense_grad_for_rank(2, 8)
    rows = rows_from_dense(jnp.asarray(g), max_rows=6)
    assert rows.values.shape == (6, DIM)
    back = np.asarray(rows_to_dense(rows))
    assert np.allclose(back, g)


def test_rows_from_dense_requires_2d():
    with pytest.raises(ValueError):
        rows_from_dense(jnp.zeros((4,)), 2)


def test_traced_sparse_allreduce_matches_dense():
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    dense = np.stack([dense_grad_for_rank(r, n) for r in range(n)])
    expect = dense.mean(axis=0)

    def step(g):
        rows = rows_from_dense(g, max_rows=4)
        reduced = sparse_allreduce(rows, op=hvd.ReduceOp.AVERAGE)
        return rows_to_dense(reduced)

    fn = jax.jit(jax.shard_map(
        lambda g: step(g[0])[None], mesh=mesh,
        in_specs=P(axis), out_specs=P(axis), check_vma=False))
    sharded = jax.device_put(dense, NamedSharding(mesh, P(axis)))
    out = np.asarray(fn(sharded))
    for r in range(n):
        assert np.allclose(out[r], expect, atol=1e-6), f"rank {r}"


def test_traced_sparse_sum():
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    dense = np.stack([dense_grad_for_rank(r, n) for r in range(n)])
    expect = dense.sum(axis=0)

    def step(g):
        reduced = sparse_allreduce(rows_from_dense(g, max_rows=4),
                                   op=hvd.ReduceOp.SUM)
        return rows_to_dense(reduced)

    fn = jax.jit(jax.shard_map(
        lambda g: step(g[0])[None], mesh=mesh,
        in_specs=P(axis), out_specs=P(axis), check_vma=False))
    out = np.asarray(fn(jax.device_put(dense, NamedSharding(mesh, P(axis)))))
    assert np.allclose(out[0], expect, atol=1e-6)


def test_eager_sparse_allreduce():
    n = hvd.size()
    values = hvd.per_rank([jnp.full((2, DIM), float(r)) for r in range(n)])
    indices = hvd.per_rank([jnp.asarray([r, 0], jnp.int32) for r in range(n)])
    rows = SparseRows(values=values, indices=indices, num_rows=VOCAB)
    out = sparse_allreduce(rows, op=hvd.ReduceOp.SUM)
    dense = np.asarray(rows_to_dense(
        SparseRows(jnp.asarray(out.values), jnp.asarray(out.indices), VOCAB)))
    expect = np.zeros((VOCAB, DIM), np.float32)
    for r in range(n):
        expect[r] += r
        expect[0] += r
    assert np.allclose(dense, expect)


def test_sparse_rejects_min_max():
    rows = SparseRows(jnp.zeros((1, DIM)), jnp.zeros((1,), jnp.int32), VOCAB)
    with pytest.raises(ValueError):
        sparse_allreduce(rows, op=hvd.ReduceOp.MAX)


def test_sparse_as_dense_knob():
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    dense = np.stack([dense_grad_for_rank(r, n) for r in range(n)])
    expect = dense.mean(axis=0)

    def step(g):
        return sparse_allreduce_to_dense(g, max_rows=4,
                                         op=hvd.ReduceOp.AVERAGE)

    fn = jax.jit(jax.shard_map(
        lambda g: step(g[0])[None], mesh=mesh,
        in_specs=P(axis), out_specs=P(axis), check_vma=False))
    envs.set_override("SPARSE_AS_DENSE", "1")
    try:
        out = np.asarray(fn(jax.device_put(
            dense, NamedSharding(mesh, P(axis)))))
    finally:
        envs.clear_override("SPARSE_AS_DENSE")
    assert np.allclose(out[0], expect, atol=1e-6)


def test_traffic_proportional_to_rows():
    """The sparse path's collective moves max_rows-proportional data: the
    jaxpr must contain an all_gather of the (max_rows, DIM) selection and
    no psum of the full (VOCAB, DIM) table."""
    mesh, axis = hvd.mesh(), hvd.axis_name()

    def step(g):
        return rows_to_dense(sparse_allreduce(
            rows_from_dense(g, max_rows=3), op=hvd.ReduceOp.SUM))

    jaxpr = str(jax.make_jaxpr(jax.shard_map(
        lambda g: step(g[0])[None], mesh=mesh,
        in_specs=P(axis), out_specs=P(axis), check_vma=False))(
            jnp.zeros((hvd.size(), VOCAB, DIM))))
    assert "all_gather" in jaxpr
    assert not re.search(r"psum.*32,4", jaxpr)


def test_distributed_optimizer_sparse_path_matches_dense():
    """Embedding model trains identically through the sparse route and the
    dense route (AVERAGE semantics)."""
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(n * 4, 3))
    targets = rng.standard_normal((n * 4, 3, DIM)).astype(np.float32)
    params0 = {"embedding": {"table": jnp.asarray(
        rng.standard_normal((VOCAB, DIM)), jnp.float32)},
        "dense": {"w": jnp.ones((DIM,), jnp.float32)}}

    def loss_fn(p, tok, tgt):
        emb = p["embedding"]["table"][tok] * p["dense"]["w"]
        return jnp.mean((emb - tgt) ** 2)

    def make_step(tx):
        def train_step(params, opt_state, tok, tgt):
            loss, grads = jax.value_and_grad(loss_fn)(params, tok, tgt)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
        return jax.jit(jax.shard_map(
            train_step, mesh=mesh, in_specs=(P(), P(), P(axis), P(axis)),
            out_specs=(P(), P(), P()), check_vma=False))

    results = []
    for sparse in (False, True):
        kw = dict(sparse_gradient_paths=["embedding"],
                  sparse_max_rows=12) if sparse else {}
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), **kw)
        params = jax.tree.map(jnp.array, params0)
        opt_state = tx.init(params)
        step = make_step(tx)
        tok = jax.device_put(tokens, NamedSharding(mesh, P(axis)))
        tgt = jax.device_put(targets, NamedSharding(mesh, P(axis)))
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tok, tgt)
        results.append(jax.tree.map(np.asarray, params))
    dense_p, sparse_p = results
    assert np.allclose(dense_p["embedding"]["table"],
                       sparse_p["embedding"]["table"], atol=1e-5)
    assert np.allclose(dense_p["dense"]["w"], sparse_p["dense"]["w"],
                       atol=1e-5)


def test_sparse_max_rows_dict():
    from horovod_tpu.optim import _sparse_rows_for
    assert _sparse_rows_for("model/embedding/table", ["embedding"], 8) == 8
    assert _sparse_rows_for("model/dense/w", ["embedding"], 8) is None
    assert _sparse_rows_for("a/emb1/t", ["emb"], {"emb1": 4, "emb2": 6}) == 4
    with pytest.raises(ValueError):
        _sparse_rows_for("a/emb3/t", ["emb"], {"emb1": 4})


def test_sparse_path_honors_scaling_and_compression():
    """prescale/postscale/compression apply to sparse-routed leaves exactly
    as to dense ones (code-review r3 regression)."""
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    from horovod_tpu.optim import _allreduce_tree
    from horovod_tpu.ops.compression import Compression

    tree = {"emb": jnp.asarray(np.arange(VOCAB * DIM, dtype=np.float32)
                               .reshape(VOCAB, DIM)),
            "w": jnp.ones((3,), jnp.float32)}

    def reduce_with(paths):
        def inner(t):
            return _allreduce_tree(
                t, op=hvd.ReduceOp.AVERAGE, process_set=None,
                compression=Compression.fp16, prescale_factor=0.5,
                postscale_factor=2.0, axis_name=axis,
                sparse_gradient_paths=paths, sparse_max_rows=VOCAB)
        import jax as _jax
        from jax.sharding import PartitionSpec as P
        fn = _jax.jit(_jax.shard_map(
            inner, mesh=mesh, in_specs=({"emb": P(), "w": P()},),
            out_specs={"emb": P(), "w": P()}, check_vma=False))
        return _jax.tree.map(np.asarray, fn(tree))

    dense = reduce_with(None)
    sparse = reduce_with(["emb"])
    assert np.allclose(dense["emb"], sparse["emb"], rtol=1e-2)
    assert np.allclose(dense["w"], sparse["w"])


def test_sparse_path_gspmd_passthrough():
    """Under plain jit (no bound axis) the sparse route is the identity,
    matching the dense GSPMD passthrough (code-review r3 regression)."""
    tx = hvd.DistributedOptimizer(optax.sgd(0.1),
                                  sparse_gradient_paths=["emb"],
                                  sparse_max_rows=4)
    params = {"emb": jnp.ones((8, DIM)), "w": jnp.ones((3,))}
    opt_state = tx.init(params)

    @jax.jit
    def step(p, s):
        g = jax.tree.map(jnp.ones_like, p)
        upd, s = tx.update(g, s, p)
        return optax.apply_updates(p, upd), s

    p2, _ = step(params, opt_state)  # must not raise
    assert np.allclose(np.asarray(p2["emb"]), 1.0 - 0.1)
