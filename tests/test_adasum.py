"""Adasum VHDD numerics vs an independent numpy model of the reference
algorithm (``adasum.h:194-342``): recursive pairwise scale-invariant
combination over the XOR tree, with distributed-dot semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.adasum import adasum_allreduce, adasum_hierarchical_traced


def np_combine(a, b):
    dot = float(np.sum(a * b))
    na = float(np.sum(a * a))
    nb = float(np.sum(b * b))
    ac = 1.0 - dot / (2 * na) if na > 0 else 1.0
    bc = 1.0 - dot / (2 * nb) if nb > 0 else 1.0
    return ac * a + bc * b


def np_adasum(vectors):
    """Reference recursion: fold non-power-of-two tail into the head
    (adasum.h nearest_power_2), then XOR-tree pairwise combines."""
    n = len(vectors)
    p = 1
    while (p << 1) <= n:
        p <<= 1
    vecs = [v.astype(np.float64) for v in vectors]
    for i in range(n - p):
        vecs[i] = np_combine(vecs[i], vecs[p + i])
    vecs = vecs[:p]
    level = 1
    while level < p:
        new = list(vecs)
        for i in range(p):
            j = i ^ level
            a, b = (vecs[i], vecs[j]) if i < j else (vecs[j], vecs[i])
            new[i] = np_combine(a, b)
        vecs = new
        level <<= 1
    return vecs[0]


def run_adasum(per_rank_vectors, process_set=None):
    x = hvd.per_rank([jnp.asarray(v, jnp.float32) for v in per_rank_vectors],
                     process_set=process_set)
    return np.asarray(adasum_allreduce(x, process_set=process_set))


def test_identical_vectors_fixed_point():
    """Adasum of n identical vectors is the vector itself (scale
    invariance), for any world size."""
    n = hvd.size()
    v = np.linspace(-1, 1, 23).astype(np.float32)
    out = run_adasum([v] * n)
    assert np.allclose(out, v, atol=1e-5)


def test_orthogonal_vectors_sum():
    """Orthogonal vectors add (dot = 0 -> coefficients 1)."""
    n = hvd.size()
    vecs = []
    for r in range(n):
        v = np.zeros((n * 3,), np.float32)
        v[r * 3:(r + 1) * 3] = r + 1.0
        vecs.append(v)
    out = run_adasum(vecs)
    assert np.allclose(out, np.sum(vecs, axis=0), atol=1e-5)


def test_matches_numpy_model_power_of_two():
    n = hvd.size()
    rng = np.random.default_rng(0)
    vecs = [rng.standard_normal(37).astype(np.float32) for _ in range(n)]
    out = run_adasum(vecs)
    expect = np_adasum(vecs)
    assert np.allclose(out, expect, rtol=1e-4, atol=1e-5), \
        np.abs(out - expect).max()


@pytest.mark.parametrize("k", [3, 5, 6, 7])
def test_matches_numpy_model_non_power_of_two(k):
    """Subset process sets exercise non-power-of-two member counts (the
    old implementation raised NotImplementedError here)."""
    if k > hvd.size():
        pytest.skip("needs more devices")
    ps = hvd.add_process_set(list(range(k)))
    try:
        rng = np.random.default_rng(k)
        vecs = [rng.standard_normal(17).astype(np.float32)
                for _ in range(k)]
        out = run_adasum(vecs, process_set=ps)
        expect = np_adasum(vecs)
        assert np.allclose(out, expect, rtol=1e-4, atol=1e-5), \
            np.abs(out - expect).max()
    finally:
        hvd.remove_process_set(ps)


def test_traced_subset_with_groups():
    """Traced mode over the global mesh with a subset pset: members get
    the subset Adasum, non-members pass through."""
    n = hvd.size()
    if n < 4:
        pytest.skip("needs 4 devices")
    ps = hvd.add_process_set([0, 1, 2])
    try:
        mesh, axis = hvd.mesh(), hvd.axis_name()
        rng = np.random.default_rng(1)
        data = rng.standard_normal((n, 9)).astype(np.float32)

        fn = jax.jit(jax.shard_map(
            lambda x: adasum_allreduce(x[0], process_set=ps)[None],
            mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False))
        out = np.asarray(fn(jax.device_put(
            data, NamedSharding(mesh, P(axis)))))
        expect = np_adasum([data[i] for i in range(3)])
        for r in range(3):
            assert np.allclose(out[r], expect, rtol=1e-4, atol=1e-5), r
        for r in range(3, n):
            assert np.allclose(out[r], data[r])  # non-members untouched
    finally:
        hvd.remove_process_set(ps)


def test_hierarchical_adasum():
    """ICI sum + DCN Adasum + ICI gather (AdasumGpuAllreduceOp analog):
    with identical vectors inside each ICI island, equals the Adasum of
    the island sums."""
    n = hvd.size()
    if n % 2:
        pytest.skip("needs even device count")
    ici = 2
    from horovod_tpu.ops.hierarchical import hierarchical_mesh
    hmesh = hierarchical_mesh(ici)
    rng = np.random.default_rng(2)
    per_island = [rng.standard_normal(11).astype(np.float32)
                  for _ in range(n // ici)]
    data = np.stack([per_island[r // ici] for r in range(n)])

    fn = jax.jit(jax.shard_map(
        lambda x: adasum_hierarchical_traced(x[0], "hvd_ici", "hvd_dcn")[None],
        mesh=hmesh, in_specs=P(("hvd_dcn", "hvd_ici")),
        out_specs=P(("hvd_dcn", "hvd_ici")), check_vma=False))
    out = np.asarray(fn(jax.device_put(
        data, NamedSharding(hmesh, P(("hvd_dcn", "hvd_ici"))))))
    expect = np_adasum([v * ici for v in per_island])
    assert np.allclose(out[0], expect, rtol=1e-4, atol=1e-4), \
        np.abs(out[0] - expect).max()


def test_bandwidth_shape_is_vhdd():
    """The compiled program must slice before permuting (halving): the
    jaxpr's ppermute operands shrink with depth instead of staying full
    size."""
    mesh, axis = hvd.mesh(), hvd.axis_name()
    n = hvd.size()
    if n < 4:
        pytest.skip("needs 4+ devices")
    jaxpr = str(jax.make_jaxpr(jax.shard_map(
        lambda x: adasum_allreduce(x[0])[None], mesh=mesh,
        in_specs=P(axis), out_specs=P(axis), check_vma=False))(
            jnp.zeros((n, 64), jnp.float32)))
    import re
    sizes = [int(m) for m in re.findall(
        r"f32\[(\d+)\] = ppermute", jaxpr)]
    assert sizes, "no ppermute found"
    assert min(sizes) < 64, f"no halving observed: {sizes}"
