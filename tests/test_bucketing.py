"""Bucketed backward-pass overlap (ISSUE 6 tentpole b): the eager
DistributedOptimizer/value_and_grad gradient sync partitions the dense
gradient pytree into HVD_BUCKET_BYTES-bounded buckets (stable
reverse-traversal order), issues each bucket as its own flushed async
grouped allreduce, and reassembles — numerics identical to the
whole-tree call, composition rank-deterministic."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.ops import fusion_cycle
from horovod_tpu.optim import _allreduce_tree, _bucket_layout, _leaf_nbytes
from horovod_tpu.ops.reduce_ops import ReduceOp
from horovod_tpu.utils import envs

N = 8


@pytest.fixture(autouse=True)
def _quiet_timer(monkeypatch):
    # every bucket flush must come from the explicit "bucket" trigger so
    # flush compositions are deterministic in the history assertions
    monkeypatch.setenv("HVD_CYCLE_TIME", "2000")
    monkeypatch.setenv("HVD_PENDING_CYCLE_TIME", "2000")
    fusion_cycle.reset()
    yield
    fusion_cycle.reset()


# ------------------------------------------------------------ bucket layout

def test_bucket_layout_reverse_order_and_cap():
    # reverse traversal: the LAST leaves (first gradients the backward
    # pass produces) fill the first bucket
    assert _bucket_layout([4, 4, 4, 4], 8) == [[3, 2], [1, 0]]
    # remainder forms the trailing bucket
    assert _bucket_layout([4, 4, 4], 8) == [[2, 1], [0]]
    # everything fits one bucket
    assert _bucket_layout([1, 2, 3], 100) == [[2, 1, 0]]


def test_bucket_layout_edge_cases():
    # single giant leaf: its own bucket, never split
    assert _bucket_layout([100], 8) == [[0]]
    # a giant leaf mid-tree doesn't absorb neighbors
    assert _bucket_layout([4, 100, 4], 8) == [[2], [1], [0]]
    # empty tree
    assert _bucket_layout([], 8) == []
    # cap smaller than every leaf: one bucket per leaf, reverse order
    assert _bucket_layout([10, 10, 10], 4) == [[2], [1], [0]]


def test_leaf_nbytes(hvd):
    assert _leaf_nbytes(jnp.zeros((10,), jnp.float32)) == 40
    assert _leaf_nbytes(jnp.zeros((10,), jnp.bfloat16)) == 20
    # PerRank bundles drop the rank axis (per-rank payload)
    pr = hvd.per_rank([jnp.zeros((4,), jnp.float32)] * N)
    assert _leaf_nbytes(pr) == 16


# ------------------------------------------------------- numerics parity

def _grad_tree(hvd, mult=1.0):
    return {
        "w1": hvd.per_rank([jnp.full((300,), (r + 1) * mult, jnp.float32)
                            for r in range(N)]),
        "inner": {
            "w2": hvd.per_rank([jnp.full((700,), (r + 1) * 2 * mult,
                                         jnp.float32) for r in range(N)]),
            "w3": hvd.per_rank([jnp.full((40,), (r + 1) * 3 * mult,
                                         jnp.float32) for r in range(N)]),
        },
        "w4": hvd.per_rank([jnp.full((500,), (r + 1) * 4 * mult,
                                     jnp.float32) for r in range(N)]),
    }


def _assert_trees_close(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x).astype(np.float32),
                                   np.asarray(y).astype(np.float32))


def test_bucketed_matches_whole_tree(hvd, monkeypatch):
    grads = _grad_tree(hvd)
    monkeypatch.setenv("HVD_BUCKET_BYTES", "0")
    whole = _allreduce_tree(
        grads, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.none, prescale_factor=1.0,
        postscale_factor=1.0, axis_name=None)
    monkeypatch.setenv("HVD_BUCKET_BYTES", "2048")
    fusion_cycle.reset()
    bucketed = _allreduce_tree(
        grads, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.none, prescale_factor=1.0,
        postscale_factor=1.0, axis_name=None)
    _assert_trees_close(whole, bucketed)
    st = hvd.fusion_stats()
    assert st["flushes"]["bucket"] >= 2  # really went through buckets


def test_bucketed_scaling_factors(hvd, monkeypatch):
    grads = _grad_tree(hvd)
    monkeypatch.setenv("HVD_BUCKET_BYTES", "0")
    whole = _allreduce_tree(
        grads, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.none, prescale_factor=0.5,
        postscale_factor=2.0, axis_name=None)
    monkeypatch.setenv("HVD_BUCKET_BYTES", "2048")
    bucketed = _allreduce_tree(
        grads, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.none, prescale_factor=0.5,
        postscale_factor=2.0, axis_name=None)
    _assert_trees_close(whole, bucketed)


def test_bucketed_mixed_dtype_compression(hvd, monkeypatch):
    """Mixed f32/bf16 leaves with fp16 wire compression: each bucket's
    grouped dispatch routes compression into the wire fusion exactly like
    the whole-tree call."""
    grads = {
        "a": hvd.per_rank([jnp.full((256,), float(r + 1), jnp.float32)
                           for r in range(N)]),
        "b": hvd.per_rank([jnp.full((256,), float(r + 1), jnp.bfloat16)
                           for r in range(N)]),
        "c": hvd.per_rank([jnp.full((512,), (r + 1) * 0.5, jnp.float32)
                           for r in range(N)]),
    }
    monkeypatch.setenv("HVD_BUCKET_BYTES", "0")
    whole = _allreduce_tree(
        grads, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.fp16, prescale_factor=1.0,
        postscale_factor=1.0, axis_name=None)
    monkeypatch.setenv("HVD_BUCKET_BYTES", "1024")
    bucketed = _allreduce_tree(
        grads, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.fp16, prescale_factor=1.0,
        postscale_factor=1.0, axis_name=None)
    _assert_trees_close(whole, bucketed)
    # decompress inside the grouped dispatch restores source dtypes, same
    # as the whole-tree call
    assert bucketed["a"].dtype == whole["a"].dtype
    assert bucketed["b"].dtype == whole["b"].dtype


def test_empty_tree_and_single_giant_leaf(hvd, monkeypatch):
    monkeypatch.setenv("HVD_BUCKET_BYTES", "1024")
    assert _allreduce_tree(
        {}, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.none, prescale_factor=1.0,
        postscale_factor=1.0, axis_name=None) == {}
    # a single leaf bigger than the cap takes the whole-tree fallback
    giant = {"w": hvd.per_rank([jnp.full((4096,), float(r + 1), jnp.float32)
                                for r in range(N)])}
    out = _allreduce_tree(
        giant, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.none, prescale_factor=1.0,
        postscale_factor=1.0, axis_name=None)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.full((4096,), 36.0))


def test_bucketed_distributed_optimizer_step(hvd, monkeypatch):
    """End-to-end: DistributedOptimizer updates are identical bucketed vs
    whole-tree (the ci step-bench gate's numerics side, in-tree)."""
    params = {"a": jnp.zeros((300,)), "b": {"c": jnp.zeros((700,))}}
    grads = {
        "a": hvd.per_rank([jnp.full((300,), float(r + 1)) for r in range(N)]),
        "b": {"c": hvd.per_rank([jnp.full((700,), (r + 1) * 2.0)
                                 for r in range(N)])},
    }
    tx = hvd.DistributedOptimizer(optax.sgd(1.0, momentum=0.9))
    st = tx.init(params)
    monkeypatch.setenv("HVD_BUCKET_BYTES", "0")
    u_whole, _ = tx.update(grads, st, params)
    monkeypatch.setenv("HVD_BUCKET_BYTES", "1500")
    fusion_cycle.reset()
    u_bucketed, _ = tx.update(grads, st, params)
    _assert_trees_close(u_whole, u_bucketed)


def test_traced_update_keeps_whole_tree_path(hvd, monkeypatch):
    """Tracer leaves must never take the async bucket path (XLA owns the
    overlap there): the traced shard_map update still works and averages
    over the mesh with bucketing configured on."""
    from jax.sharding import PartitionSpec as P
    monkeypatch.setenv("HVD_BUCKET_BYTES", "64")
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    params = {"w": jnp.zeros((3,))}
    x = jnp.arange(1.0, 9.0).reshape(N, 1)

    def step(xi):
        grads = {"w": jnp.full((3,), xi[0])}
        st = tx.init(params)
        updates, _ = tx.update(grads, st, params)
        return optax.apply_updates(params, updates)["w"]

    out = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))(x)
    got = np.asarray(out).reshape(N, 3)
    np.testing.assert_allclose(got, np.full((N, 3), -4.5), rtol=1e-6)


# ------------------------------------------------------- eager chaining

def test_eager_chain_auto_off_on_cpu(monkeypatch):
    """XLA CPU's shared per-device thread pool deadlocks when consumer
    programs race an in-flight chunked collective's rendezvous, so
    'auto' must resolve off on cpu, on elsewhere, with explicit 1/0
    overriding both."""
    monkeypatch.delenv("HVD_EAGER_CHAIN", raising=False)
    assert envs.eager_chain_enabled("cpu") is False
    assert envs.eager_chain_enabled("tpu") is True
    monkeypatch.setenv("HVD_EAGER_CHAIN", "1")
    assert envs.eager_chain_enabled("cpu") is True
    monkeypatch.setenv("HVD_EAGER_CHAIN", "0")
    assert envs.eager_chain_enabled("tpu") is False


def test_grouped_synchronize_blocks_perrank_results(hvd):
    """Handle.synchronize on a grouped result list must unwrap PerRank
    elements to their arrays for the device block — jax.block_until_ready
    silently skips opaque leaves, which used to leave grouped PerRank
    results unmaterialized (and defeats the CPU no-chain guarantee)."""
    tensors = [hvd.per_rank([jnp.full((64,), float(r + 1), jnp.float32)
                             for r in range(N)]) for _ in range(3)]
    h = hvd.grouped_allreduce_async(tensors, op=hvd.Sum)
    out = h.synchronize()
    assert len(out) == 3
    for o in out:
        arr = o.array if hasattr(o, "array") else o
        np.testing.assert_allclose(np.asarray(arr)[0], np.full((64,), 36.0))


# ----------------------------------------------------------- determinism

def _normalized_history(history):
    """Flush compositions with auto-name counters mapped to order of
    first appearance (two runs draw different counter values from the
    process-wide name counters; composition equality is about structure
    and order, which is what multi-process determinism needs)."""
    mapping = {}
    out = []
    for trigger, key, names in history:
        norm = []
        for nm in names:
            base, idx = nm.rsplit(".", 1)
            base = mapping.setdefault(base, f"g{len(mapping)}")
            norm.append(f"{base}.{idx}")
        out.append((trigger, key[0], tuple(norm)))
    return out


def test_bucket_order_rank_deterministic(hvd, monkeypatch):
    """The same gradient tree fed to two fresh schedulers produces the
    identical bucket flush stream: bucket layout is a pure function of
    leaf sizes + HVD_BUCKET_BYTES, and every bucket flushes at its
    submission point ('bucket' trigger) — the PR-2/3 composition
    contract extended to the optimizer."""
    monkeypatch.setenv("HVD_BUCKET_BYTES", "2048")
    histories = []
    for run in range(2):
        fusion_cycle.reset()
        _allreduce_tree(
            _grad_tree(hvd), op=ReduceOp.SUM, process_set=None,
            compression=hvd.Compression.none, prescale_factor=1.0,
            postscale_factor=1.0, axis_name=None)
        histories.append(
            _normalized_history(fusion_cycle.scheduler().flush_history))
    assert histories[0] == histories[1]
    assert len(histories[0]) >= 2
    # every flush in the stream is an explicit bucket dispatch
    assert {t for (t, _k, _n) in histories[0]} == {"bucket"}
    # reverse traversal: the LAST dense leaf (w4) leads the first bucket
    first_names = histories[0][0][2]
    assert first_names[0].endswith(".0")


def test_bucket_layout_matches_flushed_composition(hvd, monkeypatch):
    """The flushed tensor counts per bucket equal the pure-layout
    prediction (the composition the negotiation would see multi-process)."""
    monkeypatch.setenv("HVD_BUCKET_BYTES", "2048")
    fusion_cycle.reset()
    grads = _grad_tree(hvd)
    sizes = [_leaf_nbytes(l) for l in jax.tree.leaves(grads)]
    expected = [len(b) for b in _bucket_layout(sizes, 2048)]
    _allreduce_tree(
        grads, op=ReduceOp.SUM, process_set=None,
        compression=hvd.Compression.none, prescale_factor=1.0,
        postscale_factor=1.0, axis_name=None)
    history = [names for (t, _k, names)
               in fusion_cycle.scheduler().flush_history if t == "bucket"]
    assert [len(n) for n in history] == expected
