"""HVD_DEBUG_INVARIANTS runtime checker: lock-order witness,
thread-affinity assertions, re-entrancy guard — plus the fusion-scheduler
integration (the checker wired into ``ops/fusion_cycle.py``)."""

from __future__ import annotations

import os
import sys
import threading
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from horovod_tpu.utils import invariants as inv  # noqa: E402


@pytest.fixture
def debug_invariants():
    """Enable the checker for one test, then restore the process's prior
    state exactly (CI runs this file with HVD_DEBUG_INVARIANTS=1 exported
    globally — force-deleting it would silently disable the checker for
    the stress suites that run after)."""
    prior = os.environ.get("HVD_DEBUG_INVARIANTS")
    os.environ["HVD_DEBUG_INVARIANTS"] = "1"
    inv.refresh()
    inv.reset()
    yield inv
    if prior is None:
        os.environ.pop("HVD_DEBUG_INVARIANTS", None)
    else:
        os.environ["HVD_DEBUG_INVARIANTS"] = prior
    inv.refresh()
    inv.reset()


@pytest.fixture
def sched_check():
    """Route the constructors through the hvdsched cooperative scheduler
    for one test (mirrors the debug_invariants fixture; the two knobs
    are exercised sequentially — under HVD_SCHED_CHECK the cooperative
    primitives take precedence over the witness's tracked ones)."""
    prior = os.environ.get("HVD_SCHED_CHECK")
    os.environ["HVD_SCHED_CHECK"] = "1"
    inv.refresh()
    yield inv
    if prior is None:
        os.environ.pop("HVD_SCHED_CHECK", None)
    else:
        os.environ["HVD_SCHED_CHECK"] = prior
    inv.refresh()


@pytest.fixture
def checker_disabled():
    """Force the cached enabled flag off without touching the
    environment (the flag is what every assert site reads)."""
    old = inv._ENABLED
    inv._ENABLED = False
    yield inv
    inv._ENABLED = old


# ---------------------------------------------------------------------------
# lock-order witness
# ---------------------------------------------------------------------------

class TestLockOrderWitness:
    def test_inversion_raises_with_both_stacks(self, debug_invariants):
        a = inv.make_lock("test.a")
        b = inv.make_lock("test.b")
        with a:
            with b:
                pass
        with pytest.raises(inv.InvariantViolation) as exc:
            with b:
                with a:
                    pass
        msg = str(exc.value)
        assert "lock-order" in msg
        assert "earlier acquisition" in msg
        assert "current acquisition" in msg
        assert "test.a" in msg and "test.b" in msg
        assert inv.report()["counts"]["lock-order"] == 1

    def test_inversion_detected_across_threads(self, debug_invariants):
        a = inv.make_lock("test.a")
        b = inv.make_lock("test.b")

        def t1():
            with a:
                with b:
                    pass

        t = threading.Thread(target=t1)
        t.start()
        t.join()
        with pytest.raises(inv.InvariantViolation):
            with b:
                with a:
                    pass

    def test_violation_raised_before_blocking(self, debug_invariants):
        # the witness must report the potential deadlock, not exhibit it:
        # the inversion raises even while the other thread HOLDS the lock
        a = inv.make_lock("test.a")
        b = inv.make_lock("test.b")
        with a:
            with b:
                pass
        a.acquire()  # now b -> a would block forever without the witness
        try:
            with pytest.raises(inv.InvariantViolation):
                with b:
                    with a:
                        pass
        finally:
            a.release()

    def test_transitive_cycle_detected(self, debug_invariants):
        # A -> B and B -> C recorded; C -> A closes a 3-cycle that no
        # pairwise check would see.
        a = inv.make_lock("test.a")
        b = inv.make_lock("test.b")
        c = inv.make_lock("test.c")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(inv.InvariantViolation) as exc:
            with c:
                with a:
                    pass
        assert "test.a -> test.b -> test.c" in str(exc.value)
        assert inv.report()["counts"]["lock-order"] == 1

    def test_consistent_order_is_clean(self, debug_invariants):
        a = inv.make_lock("test.a")
        b = inv.make_lock("test.b")
        for _ in range(3):
            with a:
                with b:
                    pass
        with b:  # sequential, not nested: no edge
            pass
        with a:
            pass
        assert inv.report()["counts"]["lock-order"] == 0

    def test_rlock_reentrancy_is_not_an_edge(self, debug_invariants):
        r = inv.make_rlock("test.r")
        with r:
            with r:
                pass
        assert inv.report()["counts"]["lock-order"] == 0

    def test_condition_wait_keeps_held_state(self, debug_invariants):
        cv = inv.make_condition("test.cv")
        outer = inv.make_lock("test.outer")
        done = []

        def consumer():
            with cv:
                while not done:
                    cv.wait(0.05)

        t = threading.Thread(target=consumer)
        t.start()
        with outer:
            with cv:
                done.append(1)
                cv.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        assert inv.held_locks() == ()
        # outer -> cv was recorded; cv -> outer must now raise
        with pytest.raises(inv.InvariantViolation):
            with cv:
                with outer:
                    pass

    def test_disabled_returns_plain_primitives(self, checker_disabled):
        assert not inv.enabled()
        assert isinstance(inv.make_lock("x"), type(threading.Lock()))
        assert inv.make_condition("x").__class__ is threading.Condition


# ---------------------------------------------------------------------------
# thread-affinity + holding assertions
# ---------------------------------------------------------------------------

class TestAffinityAssertions:
    def test_assert_holding_passes_under_lock(self, debug_invariants):
        mu = inv.make_lock("test.mu")
        with mu:
            inv.assert_holding(mu, "guarded mutation")

    def test_assert_holding_raises_without_lock(self, debug_invariants):
        mu = inv.make_lock("test.mu")
        with pytest.raises(inv.InvariantViolation) as exc:
            inv.assert_holding(mu, "guarded mutation")
        assert "guarded mutation" in str(exc.value)
        assert inv.report()["counts"]["lock-held"] == 1

    def test_assert_thread(self, debug_invariants):
        other = threading.Thread(target=lambda: None)
        inv.assert_thread(None, "no owner yet")  # no-op
        inv.assert_thread(threading.current_thread(), "self is fine")
        with pytest.raises(inv.InvariantViolation):
            inv.assert_thread(other, "executor-private state")
        assert inv.report()["counts"]["thread-affinity"] == 1

    def test_counters_without_raise(self, debug_invariants):
        inv.raise_on_violation = False
        try:
            mu = inv.make_lock("test.mu")
            inv.assert_holding(mu, "mutation")
            inv.assert_holding(mu, "mutation")
        finally:
            inv.raise_on_violation = True
        rep = inv.report()
        assert rep["counts"]["lock-held"] == 2
        assert len(rep["violations"]) == 2

    def test_disabled_asserts_are_noops(self, checker_disabled):
        mu = inv.make_lock("test.mu")
        inv.assert_holding(mu, "whatever")
        inv.assert_thread(threading.Thread(target=lambda: None), "whatever")
        inv.assert_outside("nowhere", "whatever")


# ---------------------------------------------------------------------------
# re-entrancy guard
# ---------------------------------------------------------------------------

class TestReentrancyGuard:
    def test_assert_outside_raises_inside_section(self, debug_invariants):
        with inv.section("flush"):
            with pytest.raises(inv.InvariantViolation):
                inv.assert_outside("flush", "enqueue during flush")
        inv.assert_outside("flush", "after exit is fine")
        assert inv.report()["counts"]["reentrancy"] == 1

    def test_issue_lock_held_tracks_wrapped_calls(self, debug_invariants):
        from horovod_tpu.ops import program_issue
        probe = []
        wrapped = program_issue.issue_serialized(
            lambda: probe.append(program_issue.issue_lock_held()))
        assert not program_issue.issue_lock_held()
        wrapped()
        assert probe == [True]
        assert not program_issue.issue_lock_held()

    def test_sections_are_per_thread(self, debug_invariants):
        errors = []

        def other():
            try:
                inv.assert_outside("flush", "other thread")
            except inv.InvariantViolation as e:  # pragma: no cover
                errors.append(e)

        with inv.section("flush"):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert errors == []


# ---------------------------------------------------------------------------
# agreement with the hvdsched schedule checker (docs/schedule_checker.md)
# ---------------------------------------------------------------------------


def _inversion(a_name: str, b_name: str):
    """The canonical two-lock inversion, built through whatever the
    constructors currently return (tracked under HVD_DEBUG_INVARIANTS,
    cooperative under HVD_SCHED_CHECK)."""
    a = inv.make_lock(a_name)
    b = inv.make_lock(b_name)

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    return ab, ba


class TestHvdschedAgreement:
    """The lock-order witness (this module) and hvdsched (the schedule
    explorer) are two detectors for the same bug class; on the same
    seeded inversion they must agree: the identical lock-order edge,
    each reported with both participating stacks."""

    EDGE = ("agree.a", "agree.b")

    def test_same_inversion_same_edge_both_stacks(self, debug_invariants):
        from tools.hvdsched import SchedFailure, explore

        # -- detector 1: the witness, on the OS schedule ----------------
        ab, ba = _inversion(*self.EDGE)
        ab()
        with pytest.raises(inv.InvariantViolation) as witness_exc:
            ba()
        witness_msg = str(witness_exc.value)
        assert "agree.a -> agree.b" in witness_msg
        assert "earlier acquisition" in witness_msg  # stack 1
        assert "current acquisition" in witness_msg  # stack 2

        # -- detector 2: hvdsched, owning the schedule ------------------
        prior = os.environ.get("HVD_SCHED_CHECK")
        os.environ["HVD_SCHED_CHECK"] = "1"
        inv.refresh()
        try:
            def model():
                m_ab, m_ba = _inversion(*self.EDGE)
                t1 = inv.spawn_thread(m_ab, name="t-ab")
                t2 = inv.spawn_thread(m_ba, name="t-ba")
                inv.join_thread(t1)
                inv.join_thread(t2)

            result = explore(model, schedules=60, seed=0)
        finally:
            if prior is None:
                os.environ.pop("HVD_SCHED_CHECK", None)
            else:
                os.environ["HVD_SCHED_CHECK"] = prior
            inv.refresh()
        assert not result.ok, "hvdsched missed the inversion the witness saw"
        finding = result.findings[0]
        assert isinstance(finding, SchedFailure)
        assert finding.kind == "deadlock"
        report = str(finding)
        # the same edge, by name, with both blocked tasks' stacks
        assert "agree.a" in report and "agree.b" in report
        assert "t-ab" in report and "t-ba" in report
        assert ", in ab" in report and ", in ba" in report  # a stack each

    def test_sched_check_supersedes_witness(self, debug_invariants):
        """With both knobs set, the constructors return cooperative
        primitives that never register in the witness's held stack —
        the assert helpers must disarm rather than fire spuriously on
        every wired-in assert_holding."""
        from tools.hvdsched import primitives

        prior = os.environ.get("HVD_SCHED_CHECK")
        os.environ["HVD_SCHED_CHECK"] = "1"
        inv.refresh()
        try:
            assert not inv.enabled()
            mu = inv.make_lock("both.mu")
            assert isinstance(mu, primitives.Lock)
            with mu:
                inv.assert_holding(mu, "guarded mutation")  # no-op, no raise
            inv.assert_holding(mu, "unguarded too")  # still a no-op
        finally:
            if prior is None:
                os.environ.pop("HVD_SCHED_CHECK", None)
            else:
                os.environ["HVD_SCHED_CHECK"] = prior
            inv.refresh()
        assert inv.enabled()  # the witness re-arms once sched is off

    def test_lost_wakeup_fixture_needs_exploration(self, sched_check):
        """A missed-signal window (flag checked outside the lock): the
        witness has nothing to say (no lock-order edge, no affinity
        breach) and the default schedule happens to pass — only schedule
        exploration forces the failing interleaving. Uses the shared
        canonical fixture so the shape lives in exactly one place."""
        from tools.hvdsched import SchedFailure, explore, models, run_model

        model = models.DEMOS["lost-wakeup-demo"]
        run_model(model, seed=0)  # the default schedule is clean
        result = explore(model, schedules=60, seed=0)
        assert not result.ok, "exploration must force the missed signal"
        finding = result.findings[0]
        assert finding.kind == "lost-wakeup"
        assert "demo.cv" in str(finding)
        # the witness side of the agreement: no lock-order edge exists
        # for it to record — the bug is invisible to HVD_DEBUG_INVARIANTS
        # and the finding replays byte-for-byte from (seed, trace)
        with pytest.raises(SchedFailure) as exc:
            run_model(model, seed=finding.seed, trace=finding.trace)
        assert exc.value.kind == "lost-wakeup"
        assert exc.value.trace == finding.trace


# ---------------------------------------------------------------------------
# fusion-scheduler integration (the wired-in checks)
# ---------------------------------------------------------------------------

class TestSchedulerIntegration:
    def _scheduler(self, monkeypatch):
        from horovod_tpu.ops import fusion_cycle
        # synchronous executor: flushes run inline on the flushing thread
        monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "0")
        return fusion_cycle.FusionScheduler()

    def _opaque_entry(self, fusion_cycle, run, name="inv-test"):
        return fusion_cycle._Entry([None], False, 0, [name], run=run)

    def test_scheduler_locks_are_tracked(self, debug_invariants,
                                         monkeypatch):
        sched = self._scheduler(monkeypatch)
        assert getattr(sched._mu, "name", None) == \
            "fusion_cycle.scheduler.mu"

    def test_opaque_flush_executes_cleanly(self, debug_invariants,
                                           monkeypatch):
        from horovod_tpu.ops import fusion_cycle
        sched = self._scheduler(monkeypatch)
        spec = fusion_cycle._QueueSpec("sparse", None, None, svc=None)
        entry = self._opaque_entry(fusion_cycle, lambda: 42)
        sched.enqueue(("sparse", "k"), spec, entry)
        sched.flush_queue(("sparse", "k"), "synchronize")
        assert entry.done and entry.error is None
        assert entry.results == [42]
        assert inv.report()["violations"] == []
        sched.stop()

    def test_reentrant_enqueue_from_flush_is_caught(self, debug_invariants,
                                                    monkeypatch):
        from horovod_tpu.ops import fusion_cycle
        sched = self._scheduler(monkeypatch)
        spec = fusion_cycle._QueueSpec("sparse", None, None, svc=None)

        def reenter():
            inner = self._opaque_entry(fusion_cycle, lambda: 0, "inner")
            sched.enqueue(("sparse", "k2"), spec, inner)

        entry = self._opaque_entry(fusion_cycle, reenter, "outer")
        sched.enqueue(("sparse", "k1"), spec, entry)
        sched.flush_queue(("sparse", "k1"), "synchronize")
        assert entry.done
        assert isinstance(entry.error, inv.InvariantViolation)
        assert inv.report()["counts"]["reentrancy"] == 1
        sched.stop()

    def test_admit_slot_off_executor_thread_is_caught(self, debug_invariants,
                                                      monkeypatch):
        sched = self._scheduler(monkeypatch)
        # simulate a live executor owned by another thread
        sched._exec_thread = threading.Thread(target=lambda: None,
                                              name="fake-executor")
        with pytest.raises(inv.InvariantViolation):
            sched._admit_slot()
        assert inv.report()["counts"]["thread-affinity"] == 1
        sched._exec_thread = None
        sched.stop()
