"""Dynamic engine semantics: negotiation, mismatch ERRORs, cache, fusion,
groups, join, stall inspection.

Ports the reference's core-runtime guarantees (exercised there by real
2-process mpirun jobs in ``test/parallel/test_{torch,tensorflow}.py`` and
``test/integration/test_stall.py``) onto the in-memory multi-engine
protocol driver — same negotiation code, no processes.
"""

import json
import os
import time

import pytest

from horovod_tpu import _native, dynamic
from horovod_tpu.dynamic import (
    REQ_ALLGATHER,
    REQ_ALLREDUCE,
    REQ_BARRIER,
    REQ_BROADCAST,
    REQ_JOIN,
    DuplicateNameError,
    NativeEngine,
    and_bitvectors,
    drive_cycle,
)

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable (no g++?)")


def make_world(n, **kw):
    return [NativeEngine(world_size=n, rank=r, **kw) for r in range(n)]


def close_world(engines):
    for e in engines:
        e.close()


@pytest.fixture()
def world2():
    engines = make_world(2)
    yield engines
    close_world(engines)


@pytest.fixture()
def world4():
    engines = make_world(4)
    yield engines
    close_world(engines)


class TestNegotiation:
    def test_not_ready_until_all_ranks(self, world2):
        a, b = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        assert plans[0] == [] and plans[1] == []
        b.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        assert [r.tensor_names for r in plans[0]] == [["t"]]
        # identical plan on every rank (symmetric protocol)
        assert plans[0] == plans[1]

    def test_plans_identical_across_ranks(self, world4):
        for i, e in enumerate(world4):
            e.enqueue("x", REQ_ALLREDUCE, shape=(8,))
            e.enqueue(f"y{i}", REQ_ALLREDUCE, shape=(2,))
        plans = drive_cycle(world4)
        assert plans[0] == plans[1] == plans[2] == plans[3]
        # only "x" is globally ready
        names = [n for r in plans[0] for n in r.tensor_names]
        assert names == ["x"]

    def test_ordering_by_first_submission(self, world2):
        a, b = world2
        a.enqueue("late", REQ_ALLREDUCE, shape=(1000000,), dtype=1)
        drive_cycle(world2)
        a.enqueue("early", REQ_ALLREDUCE, shape=(4,))
        b.enqueue("early", REQ_ALLREDUCE, shape=(4,))
        b.enqueue("late", REQ_ALLREDUCE, shape=(1000000,), dtype=1)
        plans = drive_cycle(world2)
        names = [n for r in plans[0] for n in r.tensor_names]
        # "late" was first submitted (cycle 1) so it schedules first
        assert names == ["late", "early"]

    def test_duplicate_name_rejected_while_pending(self, world2):
        a, _ = world2
        a.enqueue("d", REQ_ALLREDUCE, shape=(4,))
        with pytest.raises(DuplicateNameError, match="d"):
            a.enqueue("d", REQ_ALLREDUCE, shape=(4,))

    def test_name_reusable_after_completion(self, world2):
        a, b = world2
        for e in world2:
            e.enqueue("r", REQ_ALLREDUCE, shape=(4,))
        drive_cycle(world2)
        for e in world2:
            e.enqueue("r", REQ_ALLREDUCE, shape=(4,))  # no raise
        plans = drive_cycle(world2)
        assert [n for r in plans[0] for n in r.tensor_names] == ["r"]


class TestMismatchErrors:
    def test_shape_mismatch_is_error_response(self, world2):
        a, b = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        b.enqueue("t", REQ_ALLREDUCE, shape=(5,))
        plans = drive_cycle(world2)
        assert plans[0] == plans[1]
        (err,) = plans[0]
        assert err.is_error
        assert "Mismatched ALLREDUCE tensor shapes" in err.error_message
        assert "[4]" in err.error_message and "[5]" in err.error_message
        assert "rank 0" in err.error_message and "rank 1" in err.error_message

    def test_dtype_mismatch(self, world2):
        a, b = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,), dtype=0)
        b.enqueue("t", REQ_ALLREDUCE, shape=(4,), dtype=2)
        (err,) = drive_cycle(world2)[0]
        assert err.is_error and "Mismatched data types" in err.error_message

    def test_op_mismatch(self, world2):
        a, b = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        b.enqueue("t", REQ_ALLGATHER, shape=(4,))
        (err,) = drive_cycle(world2)[0]
        assert err.is_error
        assert "Mismatched collective operations" in err.error_message
        assert "ALLREDUCE" in err.error_message
        assert "ALLGATHER" in err.error_message

    def test_broadcast_root_mismatch(self, world2):
        a, b = world2
        a.enqueue("t", REQ_BROADCAST, shape=(4,), root_rank=0)
        b.enqueue("t", REQ_BROADCAST, shape=(4,), root_rank=1)
        (err,) = drive_cycle(world2)[0]
        assert err.is_error and "root" in err.error_message

    def test_allgather_first_dim_may_differ(self, world2):
        a, b = world2
        a.enqueue("g", REQ_ALLGATHER, shape=(2, 3))
        b.enqueue("g", REQ_ALLGATHER, shape=(5, 3))
        (resp,) = drive_cycle(world2)[0]
        assert not resp.is_error and resp.tensor_names == ["g"]
        # the negotiated per-rank first dims ride recv_splits (the ragged
        # allgatherv size exchange, collective_operations.h:143-178)
        assert resp.recv_splits == [2, 5]

    def test_allgather_dim0_digest_mismatch(self, world2):
        a, b = world2
        a.enqueue("g", REQ_ALLGATHER, shape=(2, 3), splits_crc=7)
        b.enqueue("g", REQ_ALLGATHER, shape=(5, 3), splits_crc=8)
        (err,) = drive_cycle(world2)[0]
        assert err.is_error
        assert "ALLGATHER size metadata" in err.error_message

    def test_allgather_later_dims_must_match(self, world2):
        a, b = world2
        a.enqueue("g", REQ_ALLGATHER, shape=(2, 3))
        b.enqueue("g", REQ_ALLGATHER, shape=(2, 4))
        (err,) = drive_cycle(world2)[0]
        assert err.is_error
        assert "all dimensions except the first" in err.error_message

    def test_name_reusable_after_error(self, world2):
        a, b = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        b.enqueue("t", REQ_ALLREDUCE, shape=(5,))
        drive_cycle(world2)
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        b.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        (resp,) = drive_cycle(world2)[0]
        assert not resp.is_error


class TestFusion:
    def test_same_dtype_fused_under_threshold(self, world2):
        for e in world2:
            e.enqueue("a", REQ_ALLREDUCE, shape=(4,), dtype=1)
            e.enqueue("b", REQ_ALLREDUCE, shape=(6,), dtype=1)
            e.enqueue("c", REQ_ALLREDUCE, shape=(2,), dtype=1)
        plans = drive_cycle(world2)
        (fused,) = plans[0]
        assert fused.tensor_names == ["a", "b", "c"]
        assert fused.total_bytes == (4 + 6 + 2) * 4

    def test_dtype_change_breaks_fusion(self, world2):
        for e in world2:
            e.enqueue("a", REQ_ALLREDUCE, shape=(4,), dtype=1)
            e.enqueue("b", REQ_ALLREDUCE, shape=(4,), dtype=2)
        plans = drive_cycle(world2)
        assert [r.tensor_names for r in plans[0]] == [["a"], ["b"]]

    def test_threshold_splits_buckets(self):
        engines = make_world(2, fusion_threshold=64)
        try:
            for e in engines:
                e.enqueue("a", REQ_ALLREDUCE, shape=(8,), element_size=4)
                e.enqueue("b", REQ_ALLREDUCE, shape=(8,), element_size=4)
                e.enqueue("c", REQ_ALLREDUCE, shape=(8,), element_size=4)
            plans = drive_cycle(engines)
            assert [r.tensor_names for r in plans[0]] == [["a", "b"], ["c"]]
        finally:
            close_world(engines)

    def test_barrier_never_fused(self, world2):
        for e in world2:
            e.enqueue("a", REQ_ALLREDUCE, shape=(4,))
            e.enqueue("bar", REQ_BARRIER)
            e.enqueue("b", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        kinds = [(r.type_name, r.tensor_names) for r in plans[0]]
        assert ("BARRIER", ["bar"]) in kinds


class TestGroups:
    def test_group_waits_for_all_members(self, world2):
        a, b = world2
        for e in world2:
            e.register_group(7, 2)
        for e in world2:
            e.enqueue("g1", REQ_ALLREDUCE, shape=(4,), group_id=7)
        plans = drive_cycle(world2)
        assert plans[0] == []  # g2 not yet submitted anywhere
        for e in world2:
            e.enqueue("g2", REQ_ALLREDUCE, shape=(4,), group_id=7)
        plans = drive_cycle(world2)
        names = [n for r in plans[0] for n in r.tensor_names]
        assert sorted(names) == ["g1", "g2"]


class TestJoin:
    def test_join_completes_when_all_joined(self, world2):
        a, b = world2
        a.enqueue("j", REQ_JOIN)
        plans = drive_cycle(world2)
        assert all(not p for p in plans)
        b.enqueue("j2", REQ_JOIN)
        plans = drive_cycle(world2)
        assert [r.type_name for r in plans[0]] == ["JOIN"]
        assert plans[0] == plans[1]

    def test_joined_rank_counts_ready_for_others(self, world2):
        a, b = world2
        a.enqueue("j", REQ_JOIN)
        b.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        # rank 0 joined: its absence must not block rank 1's tensor
        names = [n for r in plans[1] for n in r.tensor_names]
        assert "t" in names


class TestResponseCache:
    def test_second_cycle_hits_cache(self, world2):
        for e in world2:
            e.enqueue("c", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        assert not plans[0][0].from_cache
        for e in world2:
            e.enqueue("c", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        (resp,) = plans[0]
        assert resp.from_cache and resp.tensor_names == ["c"]
        assert plans[0] == plans[1]

    def test_no_hit_until_all_ranks_resubmit(self, world2):
        a, b = world2
        for e in world2:
            e.enqueue("c", REQ_ALLREDUCE, shape=(4,))
        drive_cycle(world2)
        a.enqueue("c", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        assert plans[0] == [] and plans[1] == []
        b.enqueue("c", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        assert plans[0][0].from_cache

    def test_changed_shape_invalidates_consistently(self, world2):
        """The ADVICE scenario: ranks enqueue the changed tensor in
        *different* cycles; invalidation is driven by the globally-ingested
        request stream so every rank erases on the same cycle and bit
        layouts never diverge."""
        a, b = world2
        for e in world2:
            e.enqueue("v", REQ_ALLREDUCE, shape=(4,))
            e.enqueue("w", REQ_ALLREDUCE, shape=(2,))
        drive_cycle(world2)
        assert a.cache_size() == b.cache_size() == 2

        # rank 0 submits changed "v" one cycle before rank 1
        a.enqueue("v", REQ_ALLREDUCE, shape=(9,))
        drive_cycle(world2)
        # both ranks must have erased "v" on the SAME cycle
        assert a.cache_size() == b.cache_size() == 1

        b.enqueue("v", REQ_ALLREDUCE, shape=(9,))
        # "w" cache entry must still be globally consistent: a cache hit
        # for "w" must be served on both ranks with aligned bit positions
        for e in world2:
            e.enqueue("w", REQ_ALLREDUCE, shape=(2,))
        plans = drive_cycle(world2)
        assert plans[0] == plans[1]
        by_name = {tuple(r.tensor_names): r for r in plans[0]}
        assert by_name[("w",)].from_cache
        assert not by_name[("v",)].from_cache  # re-negotiated after change

    def test_cache_capacity_evicts(self):
        engines = make_world(2, cache_capacity=2)
        try:
            for i in range(3):
                for e in engines:
                    e.enqueue(f"t{i}", REQ_ALLREDUCE, shape=(4,))
                drive_cycle(engines)
            assert engines[0].cache_size() == 2
            assert engines[0].cache_size() == engines[1].cache_size()
        finally:
            close_world(engines)


class TestStallInspector:
    def test_stall_reported_after_warn_threshold(self):
        engines = make_world(2, stall_warn=0.05)
        try:
            engines[0].enqueue("s", REQ_ALLREDUCE, shape=(4,))
            drive_cycle(engines)
            time.sleep(0.1)
            report, shutdown = engines[0].stall_report()
            assert not shutdown
            (entry,) = report
            assert entry.tensor_name == "s"
            assert entry.ready_ranks == [0]
            assert entry.missing_ranks(2) == [1]
            assert entry.waiting_seconds >= 0.05
        finally:
            close_world(engines)

    def test_no_stall_before_threshold(self):
        engines = make_world(2, stall_warn=30.0)
        try:
            engines[0].enqueue("s", REQ_ALLREDUCE, shape=(4,))
            drive_cycle(engines)
            report, shutdown = engines[0].stall_report()
            assert report == [] and not shutdown
        finally:
            close_world(engines)

    def test_shutdown_threshold(self):
        engines = make_world(2, stall_warn=0.01, stall_shutdown=0.05)
        try:
            engines[0].enqueue("s", REQ_ALLREDUCE, shape=(4,))
            drive_cycle(engines)
            time.sleep(0.1)
            _, shutdown = engines[0].stall_report()
            assert shutdown
        finally:
            close_world(engines)

    def test_stall_clears_when_all_arrive(self):
        engines = make_world(2, stall_warn=0.01)
        try:
            engines[0].enqueue("s", REQ_ALLREDUCE, shape=(4,))
            drive_cycle(engines)
            time.sleep(0.05)
            engines[1].enqueue("s", REQ_ALLREDUCE, shape=(4,))
            drive_cycle(engines)
            report, _ = engines[0].stall_report()
            assert report == []
        finally:
            close_world(engines)


class TestTimeline:
    def test_chrome_trace_json(self, tmp_path):
        path = str(tmp_path / "trace.json")
        e = NativeEngine(world_size=1, rank=0)
        try:
            e.timeline_start(path)
            e.timeline_record("tensor_a", "NEGOTIATE", 0)
            e.timeline_record("tensor_a", "NEGOTIATE", 1)
            e.timeline_record("tensor_b", "ALLREDUCE", 0)
            e.timeline_record("tensor_b", "ALLREDUCE", 1)
            e.timeline_record("tensor_a", "CYCLE", 2)
            e.timeline_stop()
        finally:
            e.close()
        with open(path) as f:
            events = json.load(f)  # must be valid JSON (the reference's
            # test_timeline.py validates the same way)
        names = {ev["name"] for ev in events}
        assert {"NEGOTIATE", "ALLREDUCE", "CYCLE"} <= names
        phases = {ev["ph"] for ev in events}
        assert {"B", "E", "i", "M"} <= phases
        # one lane per tensor, named via metadata events
        lanes = {ev["args"]["name"] for ev in events if ev["ph"] == "M"}
        assert lanes == {"tensor_a", "tensor_b"}

    def test_restart_same_engine(self, tmp_path):
        e = NativeEngine()
        try:
            p1, p2 = str(tmp_path / "t1.json"), str(tmp_path / "t2.json")
            e.timeline_start(p1)
            e.timeline_record("t", "A", 2)
            e.timeline_stop()
            e.timeline_start(p2)
            e.timeline_record("t", "B", 2)
            e.timeline_stop()
            for p in (p1, p2):
                with open(p) as f:
                    json.load(f)
        finally:
            e.close()


class TestAbandon:
    """Post-timeout retry path: abandon() clears local bookkeeping so a
    name can be enqueued again (the reference has no analog — its waits
    are unbounded)."""

    def test_abandon_before_send_allows_retry(self, world2):
        a, _ = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        assert a.abandon("t")
        assert not a.abandon("t")  # not outstanding anymore
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))  # must not raise

    def test_abandon_unsent_request_never_hits_the_wire(self):
        engines = make_world(2, stall_warn=0.05)
        try:
            a, b = engines
            a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
            a.abandon("t")
            plans = drive_cycle(engines)
            assert plans == [[], []]
            # past the (tiny) stall-warn threshold a ghost table entry on
            # the other rank would show up in its stall report
            time.sleep(0.1)
            report, _ = b.stall_report()
            assert report == []
        finally:
            close_world(engines)

    def test_retry_with_different_metadata_rejected(self, world2):
        a, _ = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        drive_cycle(world2)  # request went out; table entry live
        assert a.abandon("t")
        with pytest.raises(DuplicateNameError, match="different"):
            a.enqueue("t", REQ_ALLREDUCE, shape=(8,))
        # matching retry still fine
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))

    def test_retry_after_sent_reattaches_no_ghost(self, world2):
        a, b = world2
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        drive_cycle(world2)  # a's request goes out; b hasn't submitted
        assert a.abandon("t")
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))  # re-attach, no new wire req
        assert a.pop_requests() == b.pop_requests()  # both serialize empty
        b.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        assert [p[0].tensor_names for p in plans] == [["t"], ["t"]]
        # fully complete everywhere: name reusable, nothing stalled
        a.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        b.enqueue("t", REQ_ALLREDUCE, shape=(4,))
        plans = drive_cycle(world2)
        assert [p[0].tensor_names for p in plans] == [["t"], ["t"]]


class TestBitvectorAnd:
    def test_and(self):
        assert and_bitvectors([b"\xff\x0f", b"\xf0\xff"]) == b"\xf0\x0f"

    def test_unequal_lengths_pad_zero(self):
        assert and_bitvectors([b"\xff", b"\xff\xff"]) == b"\xff\x00"

    def test_empty(self):
        assert and_bitvectors([]) == b""


def test_join_lets_others_finish_and_reports_metadata():
    """Joined ranks count as ready (controller.cc:268-272) and responses
    carry shapes/op metadata for zero reconstruction (JoinOp analog)."""
    from horovod_tpu.dynamic import NativeEngine, drive_cycle, REQ_JOIN

    engines = [NativeEngine(world_size=2, rank=r) for r in range(2)]
    try:
        engines[0].enqueue("g", 0, dtype=11, element_size=4, shape=(4, 2),
                           reduce_op=1, prescale=1.0, postscale=0.5)
        engines[1].enqueue("join.0", REQ_JOIN)
        plans = drive_cycle(engines)
        # rank 0's allreduce is schedulable thanks to the joined rank
        assert len(plans[0]) == 1
        resp = plans[0][0]
        assert resp.type == 0 and resp.tensor_names == ["g"]
        assert resp.shapes == [(4, 2)]
        assert resp.group_ids == [-1]
        assert resp.reduce_op == 1 and resp.postscale == 0.5
        # JOIN not yet emitted: rank 0 hasn't joined
        assert all(r.type != 3 for r in plans[1])
        engines[0].enqueue("join.0", REQ_JOIN)
        plans = drive_cycle(engines)
        joins = [r for r in plans[0] if r.type == 3]
        assert len(joins) == 1
        assert joins[0].root_rank == 0  # last ingested join = rank 0
        assert "join.0" in joins[0].tensor_names
    finally:
        for e in engines:
            e.close()


def test_reduce_param_mismatch_is_error():
    from horovod_tpu.dynamic import NativeEngine, drive_cycle

    engines = [NativeEngine(world_size=2, rank=r) for r in range(2)]
    try:
        engines[0].enqueue("p", 0, dtype=11, element_size=4, shape=(4,),
                           reduce_op=1, postscale=0.5)
        engines[1].enqueue("p", 0, dtype=11, element_size=4, shape=(4,),
                           reduce_op=1, postscale=1.0)
        plans = drive_cycle(engines)
        assert plans[0][0].is_error
        assert "Mismatched reduce parameters" in plans[0][0].error_message
    finally:
        for e in engines:
            e.close()


class TestRandomizedSymmetry:
    """Property check on the engine's core guarantee: every rank computes
    the IDENTICAL response plan from the identical ingested stream — for
    randomized op sequences, arrival staggering across cycles, fusion
    boundaries, and cache interleaving (the reference asserts the same
    through determinism of its rank-0 master protocol; this engine is
    symmetric, so the property must hold on every rank independently)."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_random_schedules_produce_identical_plans(self, seed):
        import random
        rng = random.Random(seed)
        n = rng.choice((2, 3, 4))
        engines = make_world(n)
        try:
            ops = []
            for i in range(rng.randint(8, 20)):
                kind = rng.choice((REQ_ALLREDUCE, REQ_ALLGATHER,
                                   REQ_BROADCAST, REQ_BARRIER))
                shape = (rng.randint(1, 6), rng.randint(1, 4))
                ops.append(dict(
                    name=f"op{i}", request_type=kind,
                    shape=() if kind == REQ_BARRIER else shape,
                    root_rank=rng.randrange(n)
                    if kind == REQ_BROADCAST else -1,
                    reduce_op=0 if kind == REQ_ALLREDUCE else -1))
            # repeat some names in later cycles to exercise the cache
            repeats = [dict(op) for op in rng.sample(
                ops, k=min(3, len(ops))) if op["request_type"] not in
                (REQ_BARRIER, REQ_ALLGATHER)]

            # stagger arrivals: each rank enqueues each op in a cycle
            # chosen per (rank, op) — readiness must still converge
            n_cycles = 4
            schedule = {(r, i): rng.randrange(n_cycles)
                        for r in range(n) for i in range(len(ops))}
            plans = [[] for _ in range(n)]
            for cycle in range(n_cycles + n + 2):
                for r, e in enumerate(engines):
                    for i, op in enumerate(ops):
                        if schedule.get((r, i)) == cycle:
                            e.enqueue(**op)
                    if cycle == n_cycles + 1:
                        for op in repeats:
                            e.enqueue(**op)
                for r, resp in enumerate(drive_cycle(engines)):
                    plans[r].extend(resp)  # full dataclass equality below
            # every rank saw the identical plan stream
            for r in range(1, n):
                assert plans[r] == plans[0], (seed, r)
            # and everything completed: each op name appears exactly once
            # per submission round in the plan (no drops, no duplicates)
            names = [nm for p in plans[0] for nm in p.tensor_names]
            for i, op in enumerate(ops):
                expected = 1 + sum(1 for rep in repeats
                                   if rep["name"] == op["name"])
                assert names.count(f"op{i}") == expected, (seed, i)
        finally:
            close_world(engines)


class _LoopbackTransport:
    """world=1 transport: the exchange returns this process's own frame."""

    def exchange(self, cycle, req_bytes, bits, timeout):
        return [req_bytes], [bits]


class TestAdaptiveCycle:
    """Event-driven negotiation tick (reference 1 ms CycleTimeMs rationale,
    operations.cc:499-506): fresh enqueues wake the cycle loop instead of
    waiting out the idle cadence; HVD_ADAPTIVE_CYCLE=0 restores the fixed
    sleep."""

    def _service(self, cycle_time_s):
        from horovod_tpu.engine_service import DynamicService
        return DynamicService(NativeEngine(world_size=1, rank=0),
                              _LoopbackTransport(),
                              cycle_time_s=cycle_time_s)

    def test_enqueue_wakes_the_cycle(self, monkeypatch):
        monkeypatch.delenv("HVD_ADAPTIVE_CYCLE", raising=False)
        svc = self._service(cycle_time_s=0.5)
        try:
            time.sleep(0.1)  # loop is now in its long idle sleep
            t0 = time.monotonic()
            resp = svc.negotiate("adaptive_t", REQ_ALLREDUCE, shape=(4,))
            took = time.monotonic() - t0
            assert not resp.is_error
            assert took < 0.25, f"adaptive tick did not wake the loop: {took}s"
        finally:
            svc.stop()

    def test_fixed_cadence_with_knob_off(self, monkeypatch):
        monkeypatch.setenv("HVD_ADAPTIVE_CYCLE", "0")
        svc = self._service(cycle_time_s=0.4)
        try:
            time.sleep(0.05)  # the loop entered its fixed sleep
            t0 = time.monotonic()
            svc.negotiate("fixed_t", REQ_ALLREDUCE, shape=(4,))
            took = time.monotonic() - t0
            # must wait out the remainder of the fixed cycle (enqueue at
            # ~0.05 into a 0.4 s sleep -> served no earlier than ~0.3 s)
            assert took > 0.2, f"fixed cadence was not respected: {took}s"
        finally:
            svc.stop()
