"""Failure-domain suite: deterministic fault injection, the unified
retry ladder, the health watchdog, and the chaos scenarios from
docs/robustness.md — KV flaps absorbed by retries, a dead rank surfacing
as ``PeerFailureError`` on the survivors well under the exchange
deadline with no hung waiter, and the elastic driver re-forming a round
on spawn failures and watchdog peer-failure reports."""

import threading
import time

import pytest

from horovod_tpu import _native, health
from horovod_tpu.exceptions import HorovodInternalError, PeerFailureError
from horovod_tpu.runner.http_kv import KVClient, KVServer
from horovod_tpu.utils import faults, retry


@pytest.fixture()
def fault_spec(monkeypatch):
    """Install a fault spec for the duration of one test."""
    def install(spec: str) -> None:
        monkeypatch.setenv("HVD_FAULT_SPEC", spec)
        faults.refresh()
    yield install
    monkeypatch.delenv("HVD_FAULT_SPEC", raising=False)
    faults.refresh()


@pytest.fixture()
def kv_server():
    server = KVServer()
    server.start()
    yield server
    server.stop()


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------

class TestSpecParsing:
    def test_grammar_example_from_docs(self):
        rules = faults.parse_spec(
            "kv.put:error:p=0.2:seed=7;"
            "svc.exchange:delay=0.5:after=3;"
            "worker:crash:rank=1:at_step=5")
        assert [r.site for r in rules] == ["kv.put", "svc.exchange",
                                           "worker"]
        assert rules[0].action == "error"
        assert rules[0].p == 0.2 and rules[0].seed == 7
        assert rules[1].action == "delay" and rules[1].delay_s == 0.5
        assert rules[1].after == 3
        assert rules[2].action == "crash"
        assert rules[2].rank == 1 and rules[2].at_step == 5

    def test_prefix_site_match(self):
        (rule,) = faults.parse_spec("kv.*:error")
        assert rule.matches_site("kv.put")
        assert rule.matches_site("kv.get")
        assert not rule.matches_site("svc.exchange")

    @pytest.mark.parametrize("bad", [
        "kv.put",                      # no action
        "kv.put:explode",              # unknown action
        ":error",                      # empty site
        "kv.put:error:p=2.0",          # p out of range
        "kv.put:error:tries=3",        # unknown parameter
        "kv.put:error:after=soon",     # non-integer value
        ";;",                          # no rules at all
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)

    def test_refresh_rejects_bad_spec_and_disables(self, monkeypatch):
        monkeypatch.setenv("HVD_FAULT_SPEC", "kv.put:bogus")
        with pytest.raises(faults.FaultSpecError):
            faults.refresh()
        assert not faults.active()
        monkeypatch.delenv("HVD_FAULT_SPEC")
        faults.refresh()


# ---------------------------------------------------------------------------
# injection semantics
# ---------------------------------------------------------------------------

class TestInjection:
    def test_noop_fast_path_when_unset(self):
        assert not faults.active()
        assert faults._SPEC is None  # inject() is one None check
        faults.inject("kv.put")  # must be a no-op, not a lookup miss
        assert faults.stats() == {}

    def test_error_action_raises(self, fault_spec):
        fault_spec("kv.put:error")
        with pytest.raises(faults.FaultInjected) as exc:
            faults.inject("kv.put")
        assert exc.value.site == "kv.put"
        faults.inject("kv.get")  # other sites untouched

    def test_probability_is_deterministic_under_a_seed(self, fault_spec):
        def pattern(spec):
            fault_spec(spec)
            fired = []
            for i in range(200):
                try:
                    faults.inject("kv.put")
                    fired.append(0)
                except faults.FaultInjected:
                    fired.append(1)
            return fired

        a = pattern("kv.put:error:p=0.3:seed=11")
        b = pattern("kv.put:error:p=0.3:seed=11")
        c = pattern("kv.put:error:p=0.3:seed=12")
        assert a == b  # same seed: identical fire pattern
        assert a != c  # different seed: different pattern
        assert 20 < sum(a) < 110  # roughly p=0.3 over 200 draws

    def test_after_and_times_filters(self, fault_spec):
        fault_spec("s:error:after=2:times=1")
        faults.inject("s")  # call 1: skipped
        faults.inject("s")  # call 2: skipped
        with pytest.raises(faults.FaultInjected):
            faults.inject("s")  # call 3: fires
        faults.inject("s")  # times=1 exhausted
        st = faults.stats()["s:error:after=2:times=1"]
        assert st["calls"] == 4 and st["fires"] == 1

    def test_rank_and_step_filters(self, fault_spec):
        fault_spec("worker:error:rank=1:at_step=3")
        faults.inject("worker", rank=0, step=3)   # wrong rank
        faults.inject("worker", rank=1, step=2)   # wrong step
        faults.inject("worker", rank=1)           # no step context
        with pytest.raises(faults.FaultInjected):
            faults.inject("worker", rank=1, step=3)

    def test_delay_action_sleeps(self, fault_spec):
        fault_spec("slow:delay=0.2")
        t0 = time.monotonic()
        faults.inject("slow")
        assert time.monotonic() - t0 >= 0.15

    def test_crash_action_exits(self, fault_spec, monkeypatch):
        codes = []
        monkeypatch.setattr(faults, "_crash", codes.append)
        fault_spec("worker:crash:code=7")
        faults.inject("worker")
        assert codes == [7]


# ---------------------------------------------------------------------------
# retry ladder
# ---------------------------------------------------------------------------

class TestRetry:
    def test_backoff_schedule_is_deterministic_and_bounded(self):
        a = [retry.backoff_s("site", k) for k in range(1, 8)]
        b = [retry.backoff_s("site", k) for k in range(1, 8)]
        assert a == b
        # jittered 50ms * 2^(k-1) capped at 2 s, jitter within +/-25%
        for k, delay in enumerate(a, start=1):
            raw = min(0.05 * 2 ** (k - 1), 2.0)
            assert raw * 0.75 <= delay <= raw * 1.25
        # different sites de-correlate
        assert retry.backoff_s("other", 1) != retry.backoff_s("site", 1)

    def test_call_retries_then_succeeds_and_counts(self, monkeypatch):
        monkeypatch.setenv("HVD_RETRY_BACKOFF_MS", "1")
        retry.reset_stats()
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise ConnectionError("flap")
            return "ok"

        assert retry.call(flaky, what="t.flaky",
                          retry_on=(ConnectionError,)) == "ok"
        assert len(attempts) == 3
        assert retry.stats()["t.flaky"]["retries"] == 2

    def test_non_retryable_raises_immediately(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("semantic")

        with pytest.raises(ValueError):
            retry.call(bad, what="t.bad", retry_on=(ConnectionError,))
        assert len(calls) == 1

    def test_predicate_retry_on_and_giveup_counter(self, monkeypatch):
        monkeypatch.setenv("HVD_RETRY_BACKOFF_MS", "1")
        monkeypatch.setenv("HVD_RETRY_MAX_ATTEMPTS", "3")
        retry.reset_stats()
        calls = []

        def always():
            calls.append(1)
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            retry.call(always, what="t.down",
                       retry_on=lambda e: isinstance(e, ConnectionError))
        assert len(calls) == 3
        assert retry.stats()["t.down"]["giveups"] == 1

    def test_deadline_bounds_total_attempts(self, monkeypatch):
        monkeypatch.setenv("HVD_RETRY_BACKOFF_MS", "200")
        calls = []

        def always():
            calls.append(1)
            raise ConnectionError("down")

        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            retry.call(always, what="t.deadline", attempts=100,
                       retry_on=(ConnectionError,), deadline_s=0.3)
        assert time.monotonic() - t0 < 2.0
        assert len(calls) < 10

    def test_poll_intervals_respects_deadline(self):
        t0 = time.monotonic()
        ticks = sum(1 for _ in retry.poll_intervals(
            "t.poll", interval_s=0.05, deadline_s=0.3))
        elapsed = time.monotonic() - t0
        assert ticks >= 2
        assert 0.2 <= elapsed <= 1.0


# ---------------------------------------------------------------------------
# KV chaos: flaps absorbed by the retry ladder
# ---------------------------------------------------------------------------

class TestKVChaos:
    def test_put_get_absorb_injected_flaps(self, kv_server, fault_spec,
                                           monkeypatch):
        monkeypatch.setenv("HVD_RETRY_BACKOFF_MS", "1")
        retry.reset_stats()
        fault_spec("kv.put:error:p=0.5:seed=3;kv.get:error:p=0.5:seed=4")
        client = KVClient("127.0.0.1", kv_server.port)
        for i in range(20):
            client.put(f"chaos/{i}", str(i).encode())
        for i in range(20):
            assert client.get(f"chaos/{i}") == str(i).encode()
        st = retry.stats()
        assert st.get("kv.put", {}).get("retries", 0) > 0
        assert st.get("kv.get", {}).get("retries", 0) > 0
        fires = sum(r["fires"] for r in faults.stats().values())
        assert fires > 0  # the flaps actually happened

    def test_wait_survives_flaps_and_returns(self, kv_server, fault_spec,
                                             monkeypatch):
        monkeypatch.setenv("HVD_RETRY_BACKOFF_MS", "1")
        fault_spec("kv.get:error:p=0.3:seed=9")
        client = KVClient("127.0.0.1", kv_server.port)

        def late_put():
            time.sleep(0.3)
            kv_server.put("late/key", b"v")

        t = threading.Thread(target=late_put)
        t.start()
        assert client.wait("late/key", timeout=10.0,
                           poll_interval=0.05) == b"v"
        t.join()

    def test_gather_survives_flaps(self, kv_server, fault_spec, monkeypatch):
        monkeypatch.setenv("HVD_RETRY_BACKOFF_MS", "1")
        fault_spec("kv.get:error:p=0.3:seed=5")
        client = KVClient("127.0.0.1", kv_server.port)
        for r in range(3):
            kv_server.put(f"g/{r}", str(r).encode())
        got = client.gather("g", 3, timeout=10.0)
        assert got == {f"g/{r}": str(r).encode() for r in range(3)}

    def test_semantic_404_is_not_retried(self, kv_server):
        retry.reset_stats()
        client = KVClient("127.0.0.1", kv_server.port)
        assert client.get("absent/key") is None
        assert retry.stats().get("kv.get", {}).get("retries", 0) == 0


# ---------------------------------------------------------------------------
# health watchdog
# ---------------------------------------------------------------------------

class TestHealthWatchdog:
    def _watchdog(self, kv, rank, on_failure, world=2, interval=0.1,
                  timeout=0.6):
        return health.HealthWatchdog(
            kv, world, rank, prefix="t/health", on_failure=on_failure,
            interval_s=interval, timeout_s=timeout)

    def test_beating_peers_stay_alive(self, kv_server):
        failures = []
        a = self._watchdog(kv_server, 0, lambda r, why: failures.append(r))
        b = self._watchdog(kv_server, 1, lambda r, why: failures.append(r))
        a.start()
        b.start()
        try:
            time.sleep(1.0)  # > timeout: both keep beating, nobody dies
            assert failures == []
            assert a.stats()["beats_sent"] >= 3
            assert a.last_seen()[1] < 0.6
        finally:
            a.stop()
            b.stop()

    def test_silent_peer_declared_dead_within_budget(self, kv_server):
        failures = []
        done = threading.Event()

        def on_failure(rank, reason):
            failures.append((rank, reason))
            done.set()

        # rank 1 beats, then dies: its counter stops advancing
        a = self._watchdog(kv_server, 0, on_failure)
        b = self._watchdog(kv_server, 1, lambda r, w: None)
        a.start()
        b.start()
        try:
            time.sleep(0.3)  # let a observe b alive
            t0 = time.monotonic()
            b.stop()  # beats cease
            assert done.wait(5.0), "watchdog never declared the dead peer"
            elapsed = time.monotonic() - t0
            rank, reason = failures[0]
            assert rank == 1
            assert "no liveness beat" in reason
            # < timeout + a couple of beat intervals, NOT the 600 s
            # exchange deadline
            assert elapsed < 0.6 + 5 * 0.1 + 1.0
        finally:
            a.stop()
            b.stop()

    def test_never_beaten_peer_gets_startup_grace(self, kv_server):
        # Service creation is lazy (first collective), so a peer that
        # hasn't STARTED yet must not be declared dead — silence
        # detection arms only after its first beat.
        failures = []
        a = self._watchdog(kv_server, 0, lambda r, w: failures.append(r))
        a.start()
        try:
            time.sleep(1.2)  # well past timeout=0.6
            assert failures == []
            assert a.last_seen()[1] is None  # tracked, never seen
            assert "no beat observed yet" in a.describe_peers()
        finally:
            a.stop()

    def test_subset_watchdog_reports_global_ranks(self, kv_server):
        # A per-process-set service runs on set-local indices; failures
        # must surface as GLOBAL ranks or the driver blacklists the
        # wrong host.
        failures = []
        done = threading.Event()

        def on_failure(rank, reason):
            failures.append(rank)
            done.set()

        a = health.HealthWatchdog(
            kv_server, 2, 0, prefix="sub/health", on_failure=on_failure,
            interval_s=0.1, timeout_s=0.5, global_ranks=[1, 3])
        b = health.HealthWatchdog(
            kv_server, 2, 1, prefix="sub/health", on_failure=lambda r, w: 0,
            interval_s=0.1, timeout_s=0.5, global_ranks=[1, 3])
        a.start()
        b.start()
        try:
            time.sleep(0.3)
            b.stop()
            assert done.wait(5.0)
            assert failures == [3]  # global rank, not set-local 1
            assert 3 in a.last_seen()
            assert a.stats()["rank"] == 1  # our own global rank
        finally:
            a.stop()
            b.stop()

    def test_poison_record_fails_peers_fast(self, kv_server):
        failures = []
        done = threading.Event()

        def on_failure(rank, reason):
            failures.append((rank, reason))
            done.set()

        a = self._watchdog(kv_server, 0, on_failure, timeout=30.0)
        b = self._watchdog(kv_server, 1, lambda r, w: None, timeout=30.0)
        a.start()
        b.start()
        try:
            time.sleep(0.3)
            b.poison("simulated local engine failure")
            # far below the 30 s beat timeout: poison is the fast path
            assert done.wait(3.0)
            rank, reason = failures[0]
            assert rank == 1
            assert "poison" in reason
            assert "simulated local engine failure" in reason
        finally:
            a.stop()
            b.stop()

    def test_describe_peers_and_stats_shape(self, kv_server):
        a = self._watchdog(kv_server, 0, lambda r, w: None)
        a.start()
        try:
            desc = a.describe_peers()
            assert "rank 1" in desc
            st = a.stats()
            assert st["rank"] == 0 and st["world_size"] == 2
            assert 1 in st["peers_last_seen_s"]
            assert st["failed_peer"] is None
            agg = health.health_stats()
            assert any(w["rank"] == 0 for w in agg["watchdogs"])
        finally:
            a.stop()
        assert all(w["rank"] != 0 or w["beats_sent"] == 0
                   for w in health.health_stats()["watchdogs"]) or \
            health.health_stats()["watchdogs"] == []

    def test_peer_failure_error_type_and_payload(self):
        exc = health.make_peer_failure_error(3, "no beat for 31.0s",
                                             ("t1", "t2"))
        assert isinstance(exc, PeerFailureError)
        assert isinstance(exc, HorovodInternalError)  # elastic-recoverable
        assert exc.rank == 3
        assert exc.owed_tensors == ("t1", "t2")
        assert "rank 3" in str(exc) and "t1" in str(exc)


# ---------------------------------------------------------------------------
# chaos: rank death mid-negotiation -> PeerFailureError on the survivor
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not _native.available(),
                    reason="native engine unavailable (no g++?)")
class TestPeerFailureChaos:
    def _make_services(self, kv_server, monkeypatch):
        from horovod_tpu.dynamic import NativeEngine
        from horovod_tpu.engine_service import DynamicService, KVTransport
        # Fast watchdog + a small exchange deadline so the test proves
        # failure detection beats the deadline by an order of magnitude.
        monkeypatch.setenv("HVD_HEALTH_INTERVAL", "0.1")
        monkeypatch.setenv("HVD_HEALTH_TIMEOUT", "0.8")
        monkeypatch.setenv("HVD_ELASTIC_TIMEOUT", "30")
        svcs = []
        for rank in range(2):
            kv = KVClient("127.0.0.1", kv_server.port)
            transport = KVTransport(kv, 2, rank, prefix="chaos")
            svcs.append(DynamicService(
                NativeEngine(world_size=2, rank=rank), transport,
                cycle_time_s=0.02))
        return svcs

    def test_rank_death_surfaces_fast_with_no_hung_waiter(self, kv_server,
                                                          monkeypatch):
        from horovod_tpu.dynamic import REQ_ALLREDUCE
        svc0, svc1 = self._make_services(kv_server, monkeypatch)
        assert svc0.health_watchdog() is not None
        try:
            # a warm negotiation proves the pair works
            results = [None, None]

            def negotiate(svc, slot):
                try:
                    results[slot] = svc.negotiate("warm", REQ_ALLREDUCE,
                                                  shape=(4,))
                except Exception as e:  # captured for the assert
                    results[slot] = e

            threads = [threading.Thread(target=negotiate, args=(s, i))
                       for i, s in enumerate((svc0, svc1))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert not any(t.is_alive() for t in threads)
            assert not isinstance(results[0], Exception), results[0]

            # rank 0 submits; rank 1 dies (service + watchdog stop: its
            # beats cease mid-negotiation)
            err = [None]
            waited = threading.Event()

            def blocked_negotiate():
                try:
                    svc0.negotiate("owed_tensor", REQ_ALLREDUCE, shape=(4,))
                except Exception as e:
                    err[0] = e
                waited.set()

            t0 = time.monotonic()
            waiter = threading.Thread(target=blocked_negotiate)
            waiter.start()
            time.sleep(0.2)
            svc1.stop()

            assert waited.wait(10.0), "survivor's waiter hung"
            elapsed = time.monotonic() - t0
            waiter.join(timeout=5)
            assert not waiter.is_alive()  # no leaked waiter thread
            assert isinstance(err[0], PeerFailureError), err[0]
            assert err[0].rank == 1
            assert "owed_tensor" in str(err[0])
            # detection ~ HVD_HEALTH_TIMEOUT + one interval, far under the
            # 30 s exchange deadline (let alone the 600 s default)
            assert elapsed < 5.0, elapsed

            # the failed service refuses new work with the same error
            with pytest.raises(PeerFailureError):
                svc0.negotiate("post_mortem", REQ_ALLREDUCE, shape=(4,))

            # the fusion scheduler was aborted: nothing pending, executor
            # queue drained (coordinated abort step 3)
            from horovod_tpu.ops import fusion_cycle
            st = fusion_cycle.stats()
            assert st["pending_tensors"] == 0
            assert st["pipeline"]["queue_depth"] == 0
        finally:
            svc0.stop()
            svc1.stop()


# ---------------------------------------------------------------------------
# chaos: elastic driver re-forms the round on injected failures
# ---------------------------------------------------------------------------

class _FakeProc:
    def __init__(self):
        self._exit = threading.Event()
        self._code = None

    def exit(self, code):
        self._code = code
        self._exit.set()

    def wait(self, timeout=None):
        self._exit.wait(timeout)
        return self._code

    def poll(self):
        return self._code if self._exit.is_set() else None

    def terminate(self):
        if not self._exit.is_set():
            self.exit(143)


class _Harness:
    def __init__(self, host_slots, min_np, max_np=None):
        from horovod_tpu.elastic import (
            ElasticDriver,
            ElasticRendezvous,
            FixedHosts,
        )
        self.kv = KVServer()
        self.kv.start()
        self.rendezvous = ElasticRendezvous(self.kv)
        self.driver = ElasticDriver(self.rendezvous, FixedHosts(host_slots),
                                    min_np, max_np, timeout=10)
        self.procs = {}
        self.lock = threading.Lock()

    def create_worker(self, slot_info, spec_round):
        proc = _FakeProc()
        with self.lock:
            self.procs.setdefault(
                (slot_info.hostname, slot_info.local_rank), []).append(proc)
        return proc

    def wait_round(self, round_id, timeout=10.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.rendezvous.round_id >= round_id:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"round {round_id} never published "
            f"(at {self.rendezvous.round_id})")

    def stop(self):
        self.driver.stop()
        self.kv.stop()


class TestElasticChaos:
    def test_injected_spawn_failure_blacklists_and_reforms(self, fault_spec):
        # rank 1 lands on host b (2 hosts x 1 slot); its spawn fails once
        fault_spec("worker.launch:error:rank=1:times=1")
        h = _Harness({"a": 1, "b": 1}, min_np=1, max_np=2)
        try:
            h.driver.start(2, h.create_worker)
            # the failed spawn becomes a registry failure -> host b is
            # blacklisted -> a new round forms with host a only, within
            # one discovery cycle (1 s) plus scheduling slack
            h.wait_round(2, timeout=10.0)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if (h.driver._host_manager.is_blacklisted("b")
                        and h.driver.world_size() == 1):
                    break
                time.sleep(0.05)
            assert h.driver._host_manager.is_blacklisted("b")
            assert h.driver.world_size() == 1
            assert not h.driver.finished()  # the job survived the fault
        finally:
            h.stop()

    def test_watchdog_peer_report_blacklists_and_reforms(self):
        # a surviving worker's watchdog reports rank 1 dead via the KV
        # record; the driver converts it into a registry failure without
        # waiting for the dead process to exit
        h = _Harness({"a": 1, "b": 1}, min_np=1, max_np=2)
        try:
            h.driver.start(2, h.create_worker)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and len(h.procs) < 2:
                time.sleep(0.05)
            assert h.driver.world_size() == 2
            dead_host = h.driver._rank_assignments[1].hostname
            import json
            h.kv.put(health.peer_failure_key(0), json.dumps(
                {"dead_rank": 1, "reason": "no beat for 1.0s"}).encode())
            # feed through the observer exactly as a worker PUT would
            parsed = health.parse_peer_failure(
                health.peer_failure_key(0),
                h.kv.get(health.peer_failure_key(0)))
            # legacy records (no round tag) parse with round_id=-1 and
            # keep the pre-ISSUE-14 resolve-against-current behavior
            assert parsed == (1, "no beat for 1.0s", -1)
            h.driver.record_peer_failure(*parsed)
            h.wait_round(2, timeout=10.0)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if h.driver._host_manager.is_blacklisted(dead_host):
                    break
                time.sleep(0.05)
            assert h.driver._host_manager.is_blacklisted(dead_host)
            assert not h.driver.finished()
        finally:
            h.stop()

    def test_commit_site_crashes_at_the_configured_step(self, fault_spec,
                                                        monkeypatch):
        from horovod_tpu.elastic.state import ObjectState
        codes = []
        monkeypatch.setattr(faults, "_crash", codes.append)
        fault_spec("worker:crash:rank=1:at_step=2")
        state = ObjectState(lambda obj: obj, lambda: 1, epoch=0)
        state.commit()   # step 1: survives
        assert codes == []
        state.commit()   # step 2: dies
        assert codes == [1]
        other = ObjectState(lambda obj: obj, lambda: 0, epoch=0)
        other.commit()
        other.commit()   # rank 0 never crashes
        assert codes == [1]
