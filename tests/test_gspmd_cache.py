"""GSPMD cached-program fast path (ISSUE 16 tentpole): a stable
step-signature cache serves lowered+compiled jit/pjit train steps out of
the dispatch plan cache — hit across re-created closures and
structurally-identical pytrees, miss (and coexist) on sharding drift,
flush on knob-override epoch, donate the params/opt-state carry under
the alias-guard rules, and fall back to a plain traced call (no hang,
no stale program) when a cached executable rejects its inputs.
Numerics must be identical cache on/off and donation on/off."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from backend_markers import loopback_world  # noqa: F401 - fixture
from horovod_tpu.ops import dispatch_cache, gspmd_cache
from horovod_tpu.utils import envs

N = 8


@pytest.fixture(autouse=True)
def _gspmd_env():
    dispatch_cache.reset()
    gspmd_cache.reset_stats()
    yield
    dispatch_cache.reset()
    gspmd_cache.reset_stats()


def _make_step():
    # re-executed per wrapper: structurally-identical fresh closures —
    # one code constant, so the content fingerprint must match
    def train_step(params, x):
        return jax.tree.map(lambda p: p - 0.1 * x.mean(), params)
    return train_step


def _params(scale=1.0):
    return {"w": jnp.full((4, 4), scale), "b": jnp.zeros((4,))}


def _gspmd_hits():
    return dispatch_cache.stats()["hits_by_source"].get("gspmd", 0)


# ---------------------------------------------------------------- hit/miss

def test_recreated_closure_replays_without_retrace(hvd):
    x = jnp.arange(8.0)
    s1 = gspmd_cache.cached_step(_make_step())
    out1 = s1(_params(), x)
    assert s1.traces == 1
    assert dispatch_cache.stats()["gspmd_builds"] == 1

    # a FRESH wrapper over a freshly-built closure — the jit-identity
    # retrace pattern — must serve the recorded executable
    s2 = gspmd_cache.cached_step(_make_step())
    out2 = s2(_params(), x)
    assert s2.traces == 0
    assert dispatch_cache.stats()["gspmd_builds"] == 1
    assert _gspmd_hits() == 1
    for k in out1:
        np.testing.assert_allclose(np.asarray(out2[k]), np.asarray(out1[k]))


def test_structurally_identical_pytrees_share_one_program(hvd):
    x = jnp.arange(8.0)
    step = gspmd_cache.cached_step(_make_step())
    step(_params(1.0), x)
    # different leaf OBJECTS and values, same structure/avals: a hit
    step(_params(3.0), x)
    assert step.traces == 1
    assert _gspmd_hits() == 1


def test_shape_drift_is_a_miss_and_signatures_coexist(hvd):
    step = gspmd_cache.cached_step(_make_step())
    step(_params(), jnp.arange(8.0))
    step(_params(), jnp.arange(4.0))  # drift: new signature, new program
    assert step.traces == 2
    assert dispatch_cache.stats()["gspmd_builds"] == 2
    # both signatures now replay — train/eval shapes coexist
    step(_params(), jnp.arange(8.0))
    step(_params(), jnp.arange(4.0))
    assert step.traces == 2
    assert _gspmd_hits() == 2


def test_sharding_drift_is_a_miss(hvd):
    devs = jax.devices()[:N]
    mesh = Mesh(np.array(devs).reshape(N), ("dp",))
    x = jnp.arange(8.0)
    wide = {"w": jnp.ones((N, 4)), "b": jnp.zeros((N,))}
    p_repl = jax.device_put(wide, NamedSharding(mesh, P()))
    p_row = {
        "w": jax.device_put(jnp.ones((N, 4)), NamedSharding(mesh, P())),
        "b": jax.device_put(jnp.zeros((N,)), NamedSharding(mesh, P("dp"))),
    }
    step = gspmd_cache.cached_step(_make_step())
    step(p_repl, x)
    # same avals, different placement: must not replay (a program
    # compiled for the replicated layout would silently mis-place the
    # row-sharded buffers). jax's own trace cache keys on avals so no
    # NEW trace happens — the miss shows up as a second build.
    step(p_row, x)
    assert dispatch_cache.stats()["gspmd_builds"] == 2
    # and both placements now replay from their own programs
    step(p_repl, x)
    step(p_row, x)
    assert _gspmd_hits() == 2


def test_output_shardings_round_trip_into_next_step(hvd):
    # trailing-None PartitionSpec canonicalization: feeding step N's
    # outputs into step N+1 must hit, not re-record
    devs = jax.devices()[:N]
    mesh = Mesh(np.array(devs).reshape(N), ("dp",))
    p = {"w": jax.device_put(jnp.ones((N, 4)),
                             NamedSharding(mesh, P("dp", None)))}
    x = jnp.arange(8.0)
    step = gspmd_cache.cached_step(_make_step())
    p = step(p, x)
    p = step(p, x)
    assert step.traces == 1
    assert _gspmd_hits() == 1


# ------------------------------------------------------------ invalidation

def test_knob_epoch_flushes_cached_programs(hvd):
    x = jnp.arange(8.0)
    step = gspmd_cache.cached_step(_make_step())
    step(_params(), x)
    assert dispatch_cache.stats()["gspmd_builds"] == 1
    envs.set_override(envs.FUSION_THRESHOLD, 123456)
    try:
        # the override bumped the cache epoch: every plan (gspmd
        # included) is gone, so the same signature re-records (jax's
        # own lowering cache makes the rebuild cheap — no new trace —
        # but the cache must not serve the pre-override program)
        step(_params(), x)
        assert dispatch_cache.stats()["gspmd_builds"] == 2
        assert gspmd_cache.stats()["events"].get("recorded", 0) == 2
        assert _gspmd_hits() == 0
    finally:
        envs.clear_override(envs.FUSION_THRESHOLD)


def test_disabled_knob_bypasses_cache(hvd, monkeypatch):
    monkeypatch.setenv("HVD_GSPMD_CACHE", "0")
    x = jnp.arange(8.0)
    step = gspmd_cache.cached_step(_make_step())
    out = step(_params(), x)
    out2 = step(_params(), x)
    assert dispatch_cache.stats()["gspmd_builds"] == 0
    assert _gspmd_hits() == 0
    assert gspmd_cache.stats()["events"].get("bypass", 0) == 2
    for k in out:
        np.testing.assert_allclose(np.asarray(out2[k]), np.asarray(out[k]))


# ---------------------------------------------------------------- donation

def test_donation_numerics_parity_three_step_lockstep(hvd, monkeypatch):
    # force donation on (auto resolves off on CPU); CPU enforces the
    # alias check and input deletion even though memory is not recycled
    monkeypatch.setenv("HVD_GSPMD_CACHE_DONATE", "1")
    x = jnp.arange(8.0)
    step = gspmd_cache.cached_step(_make_step())
    plain = jax.jit(_make_step())

    donated, reference = _params(), _params()
    for i in range(3):
        prev = donated
        donated = step(donated, x)
        reference = plain(reference, x)
        for k in reference:
            np.testing.assert_allclose(np.asarray(donated[k]),
                                       np.asarray(reference[k]),
                                       err_msg=f"step {i} leaf {k}")
    # the carry really was donated: the previous step's buffers are gone
    with pytest.raises(RuntimeError, match="[Dd]eleted"):
        np.asarray(prev["w"])
    # and the batch input (aval absent from the outputs) was NOT donated
    assert np.asarray(x).shape == (8,)


def test_donation_alias_guard_excludes_shared_buffers(hvd, monkeypatch):
    monkeypatch.setenv("HVD_GSPMD_CACHE_DONATE", "1")

    def make_two_arg():
        def train_step(a, b, x):
            return (jax.tree.map(lambda p: p - x.mean(), a),
                    jax.tree.map(lambda p: p + x.mean(), b))
        return train_step

    shared = _params()
    x = jnp.arange(8.0)
    step = gspmd_cache.cached_step(make_two_arg())
    # the SAME tree object in two donated-eligible positions: the alias
    # guard must exclude both, so the call neither errors nor deletes
    out_a, out_b = step(shared, shared, x)
    np.testing.assert_allclose(np.asarray(shared["w"]), 1.0)
    np.testing.assert_allclose(np.asarray(out_a["w"]),
                               np.asarray(shared["w"]) - x.mean())
    np.testing.assert_allclose(np.asarray(out_b["w"]),
                               np.asarray(shared["w"]) + x.mean())


# ---------------------------------------------------------------- fallback

def test_rejecting_executable_falls_back_and_rerecords(hvd):
    x = jnp.arange(8.0)
    step = gspmd_cache.cached_step(_make_step())
    ref = step(_params(), x)
    key = step._store_key((_params(), x))
    plan = dispatch_cache.lookup(key, record_stats=False)
    assert plan is not None

    def rejecting_execute(*args):
        raise TypeError("Argument types differ from the types for which "
                        "this computation was compiled (forced)")

    plan.execute = rejecting_execute
    # signature hit, executable rejection: the call must complete with
    # correct numerics (plain traced fallback), drop the stale plan, and
    # never count a hit
    out = step(_params(), x)
    for k in ref:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(ref[k]))
    assert _gspmd_hits() == 0
    assert dispatch_cache.lookup(key, record_stats=False) is None
    assert gspmd_cache.stats()["events"].get("invalidated", 0) == 1
    # the next call re-records a fresh program and replays again
    step(_params(), x)
    step(_params(), x)
    assert dispatch_cache.stats()["gspmd_builds"] == 2
    assert _gspmd_hits() == 1


# ---------------------------------------------------------------- loopback

def test_loopback_world4_per_rank_isolation():
    import horovod_tpu as hvd

    with hvd.loopback.world(4) as w:
        def body():
            r = hvd.rank()
            dispatch_cache.reset()
            gspmd_cache.reset_stats()
            step = gspmd_cache.cached_step(_make_step())
            out1 = step({"w": jnp.full((4,), float(r))}, jnp.arange(4.0))
            out2 = step({"w": jnp.full((4,), float(r))}, jnp.arange(4.0))
            return (float(np.asarray(out1["w"])[0]),
                    float(np.asarray(out2["w"])[0]),
                    dispatch_cache.stats()["gspmd_builds"],
                    dispatch_cache.stats()["hits_by_source"].get(
                        "gspmd", 0))

        outcomes = w.run(body)
    for rank, o in enumerate(outcomes):
        v1, v2, builds, hits = o.result
        # rank-distinct inputs, rank-local caches: each rank records its
        # OWN program once and replays it once — no cross-rank bleed
        expect = rank - 0.1 * np.arange(4.0).mean()
        assert abs(v1 - expect) < 1e-6, (rank, v1)
        assert v1 == v2
        assert builds == 1, (rank, builds)
        assert hits == 1, (rank, hits)
