"""Uneven alltoall: splits semantics matching the reference
(``operations.cc:1642-1727``: per-rank send splits, negotiated recv-splits
returned as a second output) plus the engine-level splits negotiation."""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.dynamic import NativeEngine, drive_cycle


def make_inputs(n, d0=None, dim=2):
    """Rank r's rows are r*100 + row_index (identifiable)."""
    d0 = d0 if d0 is not None else n + 2
    return hvd.per_rank([
        jnp.stack([jnp.full((dim,), float(r * 100 + i)) for i in range(d0)])
        for r in range(n)]), d0


def test_uneven_alltoall_matrix():
    n = hvd.size()
    x, d0 = make_inputs(n, d0=2 * n)
    # rank i sends 1 row to even ranks, 2 rows to odd ranks (sum <= d0)
    smat = np.array([[1 if j % 2 == 0 else 2 for j in range(n)]
                     for _ in range(n)])
    assert smat.sum(axis=1).max() <= d0  # sanity of the test itself
    outputs, recv_splits = hvd.alltoall(x, splits=smat)
    for r in range(n):
        assert list(recv_splits[r]) == list(smat[:, r])
        expect_rows = []
        for j in range(n):
            off = int(smat[j, :r].sum())
            for k in range(int(smat[j, r])):
                expect_rows.append(j * 100 + off + k)
        got = np.asarray(outputs[r])
        assert got.shape[0] == sum(smat[:, r])
        assert np.allclose(got[:, 0], expect_rows), f"rank {r}"


def test_uneven_alltoall_single_row():
    n = hvd.size()
    x, d0 = make_inputs(n, d0=2 * n)
    row = [2 if j == 0 else 1 for j in range(n)]
    outputs, recv_splits = hvd.alltoall(x, splits=row)
    # every rank sends the same pattern; rank 0 receives 2 rows from each
    assert list(recv_splits[0]) == [2] * n
    for r in range(1, n):
        assert list(recv_splits[r]) == [1] * n
    got0 = np.asarray(outputs[0])
    assert got0.shape[0] == 2 * n
    # rank j's first 2 rows land at rank 0
    expect = [j * 100 + k for j in range(n) for k in range(2)]
    assert np.allclose(got0[:, 0], expect)


def test_uneven_alltoall_partial_rows_not_sent():
    """Row sums < d0: trailing rows stay home (operations.cc contract)."""
    n = hvd.size()
    x, d0 = make_inputs(n, d0=3 * n)
    row = [1] * n  # only n of 3n rows sent
    outputs, recv_splits = hvd.alltoall(x, splits=row)
    total = sum(np.asarray(o).shape[0] for o in outputs)
    assert total == n * n


def test_uneven_alltoall_validation():
    n = hvd.size()
    x, d0 = make_inputs(n)
    with pytest.raises(ValueError, match="non-negative"):
        hvd.alltoall(x, splits=[-1] + [1] * (n - 1))
    with pytest.raises(ValueError, match="exceeds"):
        hvd.alltoall(x, splits=[d0] * n)
    with pytest.raises(ValueError, match="matrix"):
        hvd.alltoall(x, splits=np.ones((2, 3), np.int64))


def test_uneven_alltoall_traced_rejected():
    import jax
    from jax.sharding import PartitionSpec as P
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()

    def inner(x):
        return hvd.alltoall(x, splits=[1] * n)

    with pytest.raises(Exception, match="eager-only"):
        jax.jit(jax.shard_map(inner, mesh=mesh, in_specs=P(axis),
                              out_specs=P(axis), check_vma=False))(
            jnp.zeros((n, n, 2)))


# --- engine-level splits negotiation ---------------------------------------

def test_engine_negotiates_recv_splits():
    n = 3
    engines = [NativeEngine(world_size=n, rank=r) for r in range(n)]
    try:
        smat = np.array([[1, 2, 3], [4, 5, 6], [7, 8, 9]], np.int32)
        for r, e in enumerate(engines):
            e.enqueue("a2a", 5, dtype=1, element_size=4, shape=(64, 2),
                      splits=tuple(smat[r]))
        plans = drive_cycle(engines)
        for r, plan in enumerate(plans):
            assert len(plan) == 1
            resp = plan[0]
            assert not resp.is_error
            assert resp.recv_splits == list(smat[:, r])
    finally:
        for e in engines:
            e.close()


def test_engine_mixed_even_uneven():
    """A rank that sends no splits contributes its even share."""
    n = 2
    engines = [NativeEngine(world_size=n, rank=r) for r in range(n)]
    try:
        engines[0].enqueue("mix", 5, dtype=1, element_size=4, shape=(8, 2),
                           splits=(3, 5))
        engines[1].enqueue("mix", 5, dtype=1, element_size=4, shape=(8, 2))
        plans = drive_cycle(engines)
        assert plans[0][0].recv_splits == [3, 4]  # rank1 even: 8/2
        assert plans[1][0].recv_splits == [5, 4]
    finally:
        for e in engines:
            e.close()


def test_engine_uneven_not_cached():
    """Same name, new splits: recv_splits must be fresh, not cache-served."""
    n = 2
    engines = [NativeEngine(world_size=n, rank=r) for r in range(n)]
    try:
        for splits0, splits1 in (((1, 2), (3, 4)), ((2, 1), (4, 3))):
            engines[0].enqueue("t", 5, dtype=1, element_size=4, shape=(8, 2),
                               splits=splits0)
            engines[1].enqueue("t", 5, dtype=1, element_size=4, shape=(8, 2),
                               splits=splits1)
            plans = drive_cycle(engines)
            assert not plans[0][0].from_cache
            assert plans[0][0].recv_splits == [splits0[0], splits1[0]]
            assert plans[1][0].recv_splits == [splits0[1], splits1[1]]
    finally:
        for e in engines:
            e.close()


def test_engine_invalid_splits():
    e = NativeEngine(world_size=2, rank=0)
    try:
        with pytest.raises(ValueError, match="invalid alltoall splits"):
            e.enqueue("bad", 5, shape=(8,), splits=(1, 2, 3))  # wrong length
        with pytest.raises(ValueError, match="invalid alltoall splits"):
            e.enqueue("bad2", 5, shape=(2,), splits=(5, 5))  # sum > dim0
        with pytest.raises(ValueError, match="invalid alltoall splits"):
            e.enqueue("bad3", 0, shape=(8,), splits=(1, 2))  # not alltoall
    finally:
        e.close()


def test_engine_reattach_requires_same_splits():
    """Post-abandon retry with different splits must be rejected (-2): other
    ranks' recv_splits were computed from the original row."""
    n = 2
    engines = [NativeEngine(world_size=n, rank=r) for r in range(n)]
    try:
        # only rank 0 submits; drive a cycle so the table entry exists with
        # rank 0 ready (rank 1 never submits -> negotiation in flight)
        engines[0].enqueue("ra", 5, dtype=1, element_size=4, shape=(8, 2),
                           splits=(3, 5))
        drive_cycle(engines)
        assert engines[0].abandon("ra")
        with pytest.raises(Exception, match="metadata|in flight"):
            engines[0].enqueue("ra", 5, dtype=1, element_size=4,
                               shape=(8, 2), splits=(5, 3))
        # matching retry re-attaches fine
        engines[0].enqueue("ra", 5, dtype=1, element_size=4, shape=(8, 2),
                           splits=(3, 5))
        engines[1].enqueue("ra", 5, dtype=1, element_size=4, shape=(8, 2),
                           splits=(1, 1))
        plans = drive_cycle(engines)
        assert plans[0][0].recv_splits == [3, 1]
    finally:
        for e in engines:
            e.close()


def test_engine_reattach_allows_per_rank_dim0():
    """Alltoall dim0 is rank-local: a retry must match THIS rank's dim0,
    not the first-ingested rank's."""
    n = 2
    engines = [NativeEngine(world_size=n, rank=r) for r in range(n)]
    try:
        engines[0].enqueue("rb", 5, dtype=1, element_size=4, shape=(4, 2))
        engines[1].enqueue("rb", 5, dtype=1, element_size=4, shape=(8, 2))
        # rank 1's request reaches rank 0 first in rank order? drive a cycle
        # with only rank 1 completing ingest: emulate via full cycle minus
        # rank 0... simplest: both ingested; but rank 1 then abandons and
        # retries with ITS dim0 (8), which differs from rank 0's (4).
        datas = [e.pop_requests() for e in engines]
        for e in engines:
            for r, d in enumerate(datas):
                e.ingest(r, d)
        assert engines[1].abandon("rb")
        engines[1].enqueue("rb", 5, dtype=1, element_size=4, shape=(8, 2))
        plans = drive_cycle(engines)
        assert plans[1][0].recv_splits == [2, 4]  # even: 4/2, 8/2
    finally:
        for e in engines:
            e.close()


def test_engine_splits_matrix_digest_mismatch_symmetric():
    """Different full matrices must ERROR on every rank, even ranks whose
    recv columns agree (code-review r3: asymmetric failure would hang the
    agreeing ranks inside the collective)."""
    n = 2
    engines = [NativeEngine(world_size=n, rank=r) for r in range(n)]
    try:
        engines[0].enqueue("dig", 5, dtype=1, element_size=4, shape=(8, 2),
                           splits=(1, 2), splits_crc=111)
        engines[1].enqueue("dig", 5, dtype=1, element_size=4, shape=(8, 2),
                           splits=(3, 4), splits_crc=222)
        plans = drive_cycle(engines)
        for plan in plans:
            assert plan[0].is_error
            assert "Mismatched ALLTOALL size metadata" in plan[0].error_message
    finally:
        for e in engines:
            e.close()
