"""Timeline: eager collectives recorded as Chrome-trace JSON.

In the spirit of the reference's ``test/parallel/test_timeline.py`` (run a
job with ``HOROVOD_TIMELINE`` set, then validate the JSON)."""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import _native, timeline

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")


@pytest.fixture()
def trace(tmp_path):
    path = str(tmp_path / "timeline.json")
    hvd.start_timeline(path)
    yield path
    if timeline.timeline_active():
        hvd.stop_timeline()


def _load(path):
    with open(path) as f:
        return json.load(f)


class TestEagerTimeline:
    def test_allreduce_recorded(self, trace):
        vals = [jnp.ones(4) * i for i in range(hvd.size())]
        hvd.allreduce(hvd.per_rank(vals), op=hvd.Sum, name="grad_w")
        hvd.stop_timeline()
        events = _load(trace)
        cats = {e.get("cat") for e in events}
        assert "grad_w" in cats
        reduce_events = [e for e in events if e.get("cat") == "grad_w"]
        assert {"B", "E"} <= {e["ph"] for e in reduce_events}
        assert any(e["name"] == "ALLREDUCE" for e in reduce_events)

    def test_many_ops_one_lane_each(self, trace):
        vals = hvd.per_rank([jnp.ones(2)] * hvd.size())
        hvd.allreduce(vals, name="a")
        hvd.allgather(vals, name="b")
        hvd.broadcast(vals, 0, name="c")
        hvd.stop_timeline()
        events = _load(trace)
        lanes = {e["args"]["name"] for e in events if e["ph"] == "M"}
        assert {"a", "b", "c"} <= lanes

    def test_unnamed_ops_use_op_label(self, trace):
        vals = hvd.per_rank([jnp.ones(2)] * hvd.size())
        hvd.allreduce(vals)
        hvd.stop_timeline()
        events = _load(trace)
        assert any(e.get("cat") == "allreduce" for e in events)

    def test_inactive_timeline_records_nothing(self, tmp_path):
        # no start_timeline: op must not fail and no file appears
        vals = hvd.per_rank([jnp.ones(2)] * hvd.size())
        hvd.allreduce(vals, name="x")
        assert not timeline.timeline_active()


class TestLauncherTimeline:
    def test_hvdrun_timeline_filename_produces_file(self, tmp_path):
        """`hvdrun --timeline-filename` must actually produce a valid
        trace (the round-1 verdict flagged this flag as silently ignored)."""
        trace_path = str(tmp_path / "hvd_timeline.json")
        worker = tmp_path / "worker.py"
        worker.write_text(
            "import os\n"
            "os.environ['XLA_FLAGS'] = "
            "'--xla_force_host_platform_device_count=2'\n"
            "import jax\n"
            "try: jax.config.update('jax_platforms', 'cpu')\n"
            "except Exception: pass\n"
            "import jax.numpy as jnp\n"
            "import horovod_tpu as hvd\n"
            "hvd.init()\n"
            "hvd.allreduce(hvd.per_rank([jnp.ones(3)] * hvd.size()), "
            "name='step_grads')\n"
            "hvd.stop_timeline()\n")
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "1",
             "--timeline-filename", trace_path, "--",
             sys.executable, str(worker)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout
        assert os.path.exists(trace_path), proc.stdout
        events = _load(trace_path)
        assert any(e.get("cat") == "step_grads" for e in events)


def test_merge_timelines(tmp_path):
    import json
    from horovod_tpu.timeline import merge_timelines

    for r in (0, 1):
        (tmp_path / f"trace.{r}").write_text(
            '[{"name": "ALLREDUCE", "cat": "g", "ph": "B", "ts": %d, '
            '"pid": 0, "tid": 0},\n' % (100 + r))  # unterminated, like a live file
    out = tmp_path / "merged.json"
    n = merge_timelines([str(tmp_path / "trace.0"), str(tmp_path / "trace.1")],
                        str(out))
    events = json.loads(out.read_text())
    assert n == len(events) == 4  # 2 events + 2 process_name metadata
    pids = {e["pid"] for e in events if e.get("name") == "ALLREDUCE"}
    assert pids == {0, 1}


def test_mark_cycles_records_instants(tmp_path):
    import json
    import horovod_tpu as hvd
    from horovod_tpu import timeline

    path = tmp_path / "cycles.json"
    hvd.start_timeline(str(path), mark_cycles=True)
    try:
        timeline.mark_cycle()
        timeline.mark_cycle()
    finally:
        hvd.stop_timeline()
    text = path.read_text().rstrip(",\n ")
    if not text.endswith("]"):
        text += "]"
    events = json.loads(text)
    cycles = [e for e in events if e.get("name") == "CYCLE"]
    assert len(cycles) == 2
    assert all(e["ph"] == "i" for e in cycles)
