"""Step capture-and-replay (ISSUE 8 tentpole): record a marked step's
flush stream once, replay the whole step's collective work as ONE cached
jitted program, and fall back to eager transparently on any divergence
(shape/dtype drift, new tensors, mid-step blocking sync, abort/elastic
re-form, knob-override epoch). Numerics must be identical capture on or
off, and no fallback path may hang or reuse a stale plan."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu.ops.fusion_cycle as fusion_cycle
from horovod_tpu.ops import dispatch_cache, step_capture
from horovod_tpu.ops.compression import Compression
from horovod_tpu.utils import envs

N = 8


@pytest.fixture(autouse=True)
def _capture_env(monkeypatch):
    # quiet timers: every flush comes from an explicit trigger so the
    # recorded compositions are deterministic; capture on for the module
    monkeypatch.setenv("HVD_CYCLE_TIME", "2000")
    monkeypatch.setenv("HVD_PENDING_CYCLE_TIME", "2000")
    monkeypatch.setenv("HVD_STEP_CAPTURE", "1")
    fusion_cycle.reset()
    dispatch_cache.reset()
    yield
    fusion_cycle.reset()
    dispatch_cache.reset()


def _tensors(hvd, shapes, mult=1.0, dtype=jnp.float32):
    return [hvd.per_rank([jnp.full(shp, (r + 1) * mult * (i + 1), dtype)
                          for r in range(N)])
            for i, shp in enumerate(shapes)]


def _step(hvd, shapes, mult=1.0, dtype=jnp.float32, compression=None):
    """One marked step: submit-then-collect over per-tensor flushed
    async allreduces (the bucketed-optimizer shape capture targets)."""
    with hvd.step_marker():
        handles = []
        for t in _tensors(hvd, shapes, mult, dtype):
            h = hvd.allreduce_async(t, op=hvd.Sum, compression=compression)
            h.flush()
            handles.append(h)
        return [np.asarray(h.synchronize()) for h in handles]


def _capture_stats(hvd):
    return hvd.fusion_stats()["capture"]


# ------------------------------------------------------------ record/replay

def test_record_then_replay_numerics_identical(hvd):
    shapes = [(64,), (33,), (128,)]
    ref = _step(hvd, shapes)  # records
    st = _capture_stats(hvd)
    assert st["recorded_steps"] == 1
    assert st["captured_flushes"] == 3
    assert st["plan_builds"] == 1
    for k in range(2, 5):
        out = _step(hvd, shapes)  # replays
        for a, b, t in zip(ref, out, _tensors(hvd, shapes)):
            expect = np.sum(np.asarray(t.array), axis=0)
            assert np.allclose(a, b)
            assert np.allclose(b, expect)
    st = _capture_stats(hvd)
    assert st["replayed_steps"] == 3
    assert st["replayed_entries"] == 9
    assert st["fallbacks"] == 0


def test_replay_serves_step_plan_hits_with_source_tag(hvd):
    shapes = [(32,), (32,)]
    _step(hvd, shapes)
    flush_hits_after_record = dispatch_cache.stats()["hits_by_source"]["flush"]
    _step(hvd, shapes)
    d = dispatch_cache.stats()
    # the replayed step serves from the step plan, not per-flush plans
    assert d["hits_by_source"]["step"] >= 1
    assert d["hits_by_source"]["flush"] == flush_hits_after_record
    assert d["step_builds"] == 1
    # replayed entries never count as flush-level dispatches, so the
    # coalesce ratio isn't silently inflated by capture
    assert hvd.fusion_stats()["dispatches"] == 2  # the record step's flushes


def test_wire_compression_replays_identically(hvd):
    shapes = [(48,), (16,)]
    ref = _step(hvd, shapes, compression=Compression.fp16)
    out = _step(hvd, shapes, compression=Compression.fp16)
    assert _capture_stats(hvd)["replayed_steps"] == 1
    for a, b in zip(ref, out):
        assert np.allclose(a, b)


def test_grouped_and_single_mixed_stream(hvd):
    with hvd.step_marker():
        g = hvd.grouped_allreduce_async(_tensors(hvd, [(8,), (24,)]),
                                        op=hvd.Sum)
        g.flush()
        s = hvd.allreduce_async(_tensors(hvd, [(40,)])[0], op=hvd.Sum)
        s.flush()
        ref = [np.asarray(x) for x in g.synchronize()] \
            + [np.asarray(s.synchronize())]
    with hvd.step_marker():
        g = hvd.grouped_allreduce_async(_tensors(hvd, [(8,), (24,)]),
                                        op=hvd.Sum)
        g.flush()
        s = hvd.allreduce_async(_tensors(hvd, [(40,)])[0], op=hvd.Sum)
        s.flush()
        out = [np.asarray(x) for x in g.synchronize()] \
            + [np.asarray(s.synchronize())]
    assert _capture_stats(hvd)["replayed_steps"] == 1
    for a, b in zip(ref, out):
        assert np.allclose(a, b)


# --------------------------------------------------------- invalidation

def test_shape_drift_invalidates_and_falls_back(hvd):
    _step(hvd, [(64,), (32,)])
    _step(hvd, [(64,), (32,)])
    assert _capture_stats(hvd)["replayed_steps"] == 1
    # shape drift: the second tensor grew — replay must fall back with
    # correct results, never serve the stale plan
    out = _step(hvd, [(64,), (48,)])
    assert out[1].shape == (48,)
    expect = np.sum(np.asarray(
        _tensors(hvd, [(64,), (48,)])[1].array), axis=0)
    assert np.allclose(out[1], expect)
    st = _capture_stats(hvd)
    assert st["fallbacks"] >= 1
    assert st["invalidations"] >= 1
    # the drifted stream re-captures and replays again
    _step(hvd, [(64,), (48,)])
    _step(hvd, [(64,), (48,)])
    assert _capture_stats(hvd)["replayed_steps"] >= 2


def test_dtype_drift_invalidates_and_falls_back(hvd):
    _step(hvd, [(64,)], dtype=jnp.float32)
    _step(hvd, [(64,)], dtype=jnp.float32)
    out = _step(hvd, [(64,)], dtype=jnp.bfloat16)
    assert out[0].dtype == jnp.bfloat16
    st = _capture_stats(hvd)
    assert st["fallbacks"] >= 1


def test_extra_tensor_invalidates_and_falls_back(hvd):
    _step(hvd, [(64,)])
    _step(hvd, [(64,)])
    # a NEW tensor appears after the recorded stream completed: the step
    # already replayed, so the extra submission lands in a completed
    # region — it must still execute correctly (normal eager path)
    with hvd.step_marker():
        h1 = hvd.allreduce_async(_tensors(hvd, [(64,)])[0], op=hvd.Sum)
        h1.flush()
        h2 = hvd.allreduce_async(_tensors(hvd, [(7,)])[0], op=hvd.Sum)
        h2.flush()
        a = np.asarray(h1.synchronize())
        b = np.asarray(h2.synchronize())
    assert np.allclose(b, np.sum(np.asarray(
        _tensors(hvd, [(7,)])[0].array), axis=0))
    assert a.shape == (64,)


def test_mid_step_synchronize_falls_back_no_hang(hvd):
    # record: two entries, each drained by its own synchronize
    with hvd.step_marker():
        h = hvd.allreduce_async(_tensors(hvd, [(64,)])[0], op=hvd.Sum)
        r1 = np.asarray(h.synchronize())
        h = hvd.allreduce_async(_tensors(hvd, [(32,)])[0], op=hvd.Sum)
        np.asarray(h.synchronize())
    # replay: the first synchronize BLOCKS before the recorded stream
    # completed — capture must execute the held prefix eagerly instead
    # of hanging on a dispatch that would only fire at stream completion
    with hvd.step_marker():
        h = hvd.allreduce_async(_tensors(hvd, [(64,)])[0], op=hvd.Sum)
        out1 = np.asarray(h.synchronize())
        h = hvd.allreduce_async(_tensors(hvd, [(32,)])[0], op=hvd.Sum)
        np.asarray(h.synchronize())
    assert np.allclose(out1, r1)
    assert _capture_stats(hvd)["fallbacks"] >= 1


def test_abort_mid_captured_step_fails_held_entries(hvd):
    """Elastic re-form / PeerFailureError teardown mid-captured-step:
    the PR-5 coordinated abort reaches capture-held entries — the waiter
    unblocks with the abort error (no hang), and the plan is dropped."""
    _step(hvd, [(64,), (32,)])  # record
    sched = fusion_cycle.scheduler()
    with hvd.step_marker():
        h = hvd.allreduce_async(_tensors(hvd, [(64,)])[0], op=hvd.Sum)
        h.flush()  # held by the armed replay
        n = sched.abort("peer rank 3 failed: PeerFailureError")
        assert n >= 1
        with pytest.raises(RuntimeError, match="aborted"):
            h.synchronize()
    st = _capture_stats(hvd)
    assert st["invalidations"] >= 1
    # the next marked step re-records against the new world
    ref = _step(hvd, [(64,), (32,)])
    out = _step(hvd, [(64,), (32,)])
    for a, b in zip(ref, out):
        assert np.allclose(a, b)


def test_knob_override_epoch_invalidates_plan(hvd):
    _step(hvd, [(64,)])
    _step(hvd, [(64,)])
    assert _capture_stats(hvd)["replayed_steps"] == 1
    builds = _capture_stats(hvd)["plan_builds"]
    # a knob override bumps the envs epoch: the dispatch cache flushes,
    # dropping the step plan — the next step re-records, never replays
    # a plan built under the old knob state
    envs.set_override(envs.FUSION_THRESHOLD, 1 << 22)
    try:
        out = _step(hvd, [(64,)])
        assert np.allclose(out[0], np.sum(np.asarray(
            _tensors(hvd, [(64,)])[0].array), axis=0))
        st = _capture_stats(hvd)
        assert st["invalidations"] >= 1
        assert st["plan_builds"] == builds + 1  # re-captured
        _step(hvd, [(64,)])
        assert _capture_stats(hvd)["replayed_steps"] == 2
    finally:
        envs.clear_override(envs.FUSION_THRESHOLD)


def test_barrier_mid_step_drains_held_entries(hvd):
    _step(hvd, [(64,), (32,)])
    with hvd.step_marker():
        # only the first of the two recorded submissions has arrived:
        # the held prefix must dispatch at the barrier-style drain
        h = hvd.allreduce_async(_tensors(hvd, [(64,), (32,)])[0],
                                op=hvd.Sum)
        h.flush()
        hvd.fusion_flush()  # barrier-style drain mid-replay
        out = np.asarray(h.synchronize())
    assert np.allclose(out, np.sum(np.asarray(
        _tensors(hvd, [(64,)])[0].array), axis=0))
    assert _capture_stats(hvd)["fallbacks"] >= 1


# ---------------------------------------------------- determinism parity

def test_two_scheduler_capture_key_parity(hvd, monkeypatch):
    """The PR-2/3 determinism contract extended to capture: two
    schedulers fed the identical stream seal byte-identical capture
    keys (auto-generated negotiation names — global counters — are
    excluded from the key by design)."""
    def capture_key(sched):
        monkeypatch.setattr(fusion_cycle, "_scheduler", sched)
        _step(__import__("horovod_tpu"), [(64,), (32,), (9,)])
        key = sched.capture._last_key
        sched.stop()
        return key

    key_a = capture_key(fusion_cycle.FusionScheduler())
    key_b = capture_key(fusion_cycle.FusionScheduler())
    assert key_a is not None
    assert key_a == key_b
    assert repr(key_a) == repr(key_b)  # byte-identical


def test_uncapturable_stream_stays_eager(hvd):
    shapes = [(16,)]
    for _ in range(3):
        with hvd.step_marker():
            h = hvd.allreduce_async(_tensors(hvd, shapes)[0], op=hvd.Sum)
            h.flush()
            g = hvd.allgather_async(jnp.ones((4,), jnp.float32))
            out = np.asarray(h.synchronize())
            gathered = np.asarray(g.synchronize())
        assert gathered.shape == (4 * N,)
        assert np.allclose(out, np.sum(np.asarray(
            _tensors(hvd, shapes)[0].array), axis=0))
    st = _capture_stats(hvd)
    assert st["replayed_steps"] == 0
    assert st["uncapturable_steps"] >= 1


def test_empty_region_keeps_plan_armed(hvd):
    # a marked region with no collectives (e.g. an eval iteration
    # between train steps) must not invalidate the capture — the next
    # non-empty step re-arms and replays
    shapes = [(64,), (32,)]
    _step(hvd, shapes)            # record
    with hvd.step_marker():
        pass                      # empty eval region
    out = _step(hvd, shapes)      # must REPLAY, not re-record
    st = _capture_stats(hvd)
    assert st["replayed_steps"] == 1, st
    assert st["recorded_steps"] == 1, st
    assert st["fallbacks"] == 0, st
    expect = np.sum(np.asarray(_tensors(hvd, shapes)[0].array), axis=0)
    assert np.allclose(out[0], expect)


def test_cache_disabled_skips_recording(hvd, monkeypatch):
    # HVD_CACHE_CAPACITY=0: a sealed plan could never be stored, so
    # capture must stay eager instead of re-recording every step
    monkeypatch.setenv("HVD_CACHE_CAPACITY", "0")
    fusion_cycle.reset()
    ref = _step(hvd, [(64,)])
    out = _step(hvd, [(64,)])
    st = _capture_stats(hvd)
    assert st["recorded_steps"] == 0
    assert st["plan_builds"] == 0
    assert st["replayed_steps"] == 0
    assert np.allclose(ref[0], out[0])


def test_svc_duplicate_names_seal_uncapturable():
    """A user name repeated within one step needs the eager path's
    name-reuse serialization (two sequential negotiation batches);
    replay's single negotiate_step round would orphan the first request
    — such a stream must seal as uncapturable, never replay-and-hang."""
    sched = fusion_cycle.FusionScheduler()
    cap = sched.capture

    class _Svc:
        pass

    svc = _Svc()
    spec = fusion_cycle._QueueSpec("allreduce", None, None, svc=svc)
    sig = (("r", (4,), "float32"),)
    dup = [
        step_capture._FlushRecord(spec, [step_capture._EntryTemplate(
            ("k",), False, 1, sig, names=("grad",))], "bucket"),
        step_capture._FlushRecord(spec, [step_capture._EntryTemplate(
            ("k",), False, 1, sig, names=("grad",))], "bucket"),
    ]
    assert cap._default_build_plan(("key",), dup) is None
    unique = [
        step_capture._FlushRecord(spec, [step_capture._EntryTemplate(
            ("k",), False, 1, sig, names=("grad.0",))], "bucket"),
        step_capture._FlushRecord(spec, [step_capture._EntryTemplate(
            ("k",), False, 1, sig, names=("grad.1",))], "bucket"),
    ]
    plan = cap._default_build_plan(("key",), unique)
    assert isinstance(plan, step_capture.StepPlan)
    sched.stop()


def test_negotiate_step_batches_one_round_and_counts():
    """The whole-step batched negotiation seam: one negotiate_many round
    for the whole request list, counted on the service."""
    from horovod_tpu.engine_service import DynamicService
    svc = DynamicService.__new__(DynamicService)
    svc.step_negotiations = 0
    rounds = []

    def fake_many(reqs, timeout=None):
        rounds.append(len(reqs))
        return ["resp"] * len(reqs)

    svc.negotiate_many = fake_many
    out = svc.negotiate_step([{"name": "a"}, {"name": "b"},
                              {"name": "c"}])
    assert rounds == [3]  # ONE round for the whole step
    assert svc.step_negotiations == 1
    assert len(out) == 3


def test_capture_disabled_is_inert(hvd, monkeypatch):
    monkeypatch.setenv("HVD_STEP_CAPTURE", "0")
    fusion_cycle.reset()
    ref = _step(hvd, [(64,)])
    out = _step(hvd, [(64,)])
    st = _capture_stats(hvd)
    assert st["recorded_steps"] == 0
    assert st["replayed_steps"] == 0
    assert np.allclose(ref[0], out[0])


# ------------------------------------------------- optimizer integration

def test_distributed_optimizer_capture_parity(hvd, monkeypatch):
    """End-to-end: the bucketed DistributedOptimizer sync marks its own
    capture region — params after 3 steps are identical capture on/off,
    and steps 2-3 replay."""
    monkeypatch.setenv("HVD_BUCKET_BYTES", "2048")

    def run(capture_on):
        monkeypatch.setenv("HVD_STEP_CAPTURE", "1" if capture_on else "0")
        fusion_cycle.reset()
        dispatch_cache.reset()
        params = {
            "a": jnp.ones((300,), jnp.float32),
            "b": {"w": jnp.full((500,), 2.0, jnp.float32)},
            "c": jnp.full((200,), 3.0, jnp.float32),
        }
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        opt = tx.init(params)
        for step in range(3):
            grads = {
                "a": hvd.per_rank([jnp.full((300,), (r + 1) * 0.01 * (step + 1),
                                            jnp.float32) for r in range(N)]),
                "b": {"w": hvd.per_rank([jnp.full((500,), (r + 1) * 0.02,
                                                  jnp.float32)
                                         for r in range(N)])},
                "c": hvd.per_rank([jnp.full((200,), (r + 1) * 0.03,
                                            jnp.float32) for r in range(N)]),
            }
            updates, opt = tx.update(grads, opt, params)
            params = optax.apply_updates(params, updates)
        import jax
        stats = hvd.fusion_stats()["capture"]
        return [np.asarray(l) for l in jax.tree.leaves(params)], stats

    off_params, _ = run(False)
    on_params, on_stats = run(True)
    assert on_stats["recorded_steps"] == 1
    assert on_stats["replayed_steps"] == 2
    assert on_stats["fallbacks"] == 0
    for a, b in zip(off_params, on_params):
        assert np.allclose(a, b)


def test_step_marker_context_manager_closes_region(hvd):
    with hvd.step_marker():
        h = hvd.allreduce_async(_tensors(hvd, [(64,)])[0], op=hvd.Sum)
        h.flush()
        h.synchronize()
    cap = fusion_cycle.scheduler().capture
    assert not cap.region_open()
    # a flush outside any region is not recorded
    h = hvd.allreduce_async(_tensors(hvd, [(64,)])[0], op=hvd.Sum)
    h.synchronize()
    assert _capture_stats(hvd)["recorded_steps"] == 1
    assert _capture_stats(hvd)["captured_flushes"] == 1
