"""Pipelined flush executor + large-tensor chunk pipelining (ISSUE 3
tentpole): flush triggers only drain queues and hand batches to a single
FIFO dispatch thread with HVD_MAX_INFLIGHT_FLUSHES slots; fused wire
buffers past HVD_PIPELINE_THRESHOLD dispatch as HVD_PIPELINE_CHUNKS chunk
programs; HVD_MAX_INFLIGHT_FLUSHES=1 restores the synchronous PR-2
behavior; composition and per-signature FIFO result order stay
deterministic under producer threads and timer fire; abort() mid-pipeline
never deadlocks."""

import threading
import time
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import dispatch_cache, fusion_cycle
from horovod_tpu.ops.collectives import _chunk_layout, _pipeline_key
from horovod_tpu.utils import envs

N = 8
LONG_CYCLE_MS = "2000"


@pytest.fixture(autouse=True)
def _fresh_scheduler(monkeypatch):
    monkeypatch.setenv("HVD_CYCLE_TIME", LONG_CYCLE_MS)
    monkeypatch.setenv("HVD_PENDING_CYCLE_TIME", LONG_CYCLE_MS)
    fusion_cycle.reset()
    yield
    fusion_cycle.reset()


def _vals(shape=(8,), dtype=jnp.float32, mult=1.0):
    return [jnp.full(shape, (i + 1) * mult, dtype) for i in range(N)]


def _sum_expected(shape=(8,), mult=1.0):
    return np.full(shape, 36.0 * mult)


# ------------------------------------------------------------- executor mode

def test_pipelined_executor_runs_flushes_off_thread(hvd, monkeypatch):
    """Default (2 slots): a threshold trigger returns before the flush
    executes; the executor thread delivers, and the pipeline stats see
    the batches."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    monkeypatch.setenv("HVD_FUSION_THRESHOLD", "100")
    handles = [hvd.allreduce_async(hvd.per_rank(_vals(mult=i + 1)),
                                   op=hvd.Sum) for i in range(4)]
    for h in handles:
        assert h._entry.event.wait(10.0)
    for i, h in enumerate(handles):
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   _sum_expected(mult=i + 1))
    st = hvd.fusion_stats()
    assert st["pipeline"]["enabled"] is True
    assert st["pipeline"]["executed"] >= 1
    assert st["pipeline"]["submitted"] == st["pipeline"]["executed"]
    assert st["pipeline"]["queue_depth"] == 0


def test_inflight_one_is_synchronous_pr2_behavior(hvd, monkeypatch):
    """HVD_MAX_INFLIGHT_FLUSHES=1: flush triggers execute inline on the
    triggering thread (the PR-2 path), the executor never engages, and
    chunking is disabled."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "1")
    monkeypatch.setenv("HVD_FUSION_THRESHOLD", "100")
    assert not envs.pipeline_enabled()
    assert _pipeline_key() is None
    handles = [hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
               for _ in range(4)]
    # the threshold trigger ran the flush synchronously before returning
    assert all(h._entry.done for h in handles)
    st = hvd.fusion_stats()
    assert st["pipeline"]["enabled"] is False
    assert st["pipeline"]["executed"] == 0
    for h in handles:
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   _sum_expected())


def test_flush_all_quiesces_executor(hvd, monkeypatch):
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    hs = [hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
          for _ in range(3)]
    hvd.barrier()  # flush_all("barrier") + quiesce
    assert all(h._entry.done for h in hs)
    st = hvd.fusion_stats()
    assert st["pipeline"]["queue_depth"] == 0
    assert st["pending_tensors"] == 0


def test_fusion_flush_api(hvd):
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    hvd.fusion_flush()
    assert h._entry.done
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected())


def test_determinism_history_with_executor_on(hvd, monkeypatch):
    """Identical call streams on two schedulers produce identical flush
    compositions with the executor on (acceptance criterion): the
    composition record is written at DRAIN time on the trigger thread,
    so executor timing can never reorder it."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    histories = []
    for _ in range(2):
        fusion_cycle.reset()
        handles = [
            hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum,
                                name="d0"),
            hvd.broadcast_async(hvd.per_rank(_vals()), 0, name="d1"),
            hvd.allreduce_async(hvd.per_rank(_vals(mult=2.0)), op=hvd.Sum,
                                name="d2"),
        ]
        fusion_cycle.scheduler().flush_all("barrier")
        histories.append(list(fusion_cycle.scheduler().flush_history))
        for h in handles:
            hvd.synchronize(h)
    assert histories[0] == histories[1]
    comps = [(key[0], names) for (_t, key, names) in histories[0]]
    assert comps[0] == ("allreduce", ("d0", "d2"))
    assert ("broadcast", ("d1",)) in comps


# --------------------------------------------------------- chunk pipelining

def test_chunk_layout_shapes():
    f32 = jnp.dtype(jnp.float32)
    # one bucket of 1024 f32 = 4 KiB, threshold 1 KiB, 4 chunks
    metas = [(f32, [0], [(1024,)], [f32])]
    import os
    os.environ["HVD_PIPELINE_THRESHOLD"] = "1024"
    os.environ["HVD_PIPELINE_CHUNKS"] = "4"
    os.environ["HVD_MAX_INFLIGHT_FLUSHES"] = "2"
    try:
        layout = _chunk_layout(metas)
        assert layout == [(0, 0, 256), (0, 256, 512), (0, 512, 768),
                          (0, 768, 1024)]
        # non-divisible total: last chunk is the remainder
        metas2 = [(f32, [0, 1], [(500,), (510,)], [f32, f32])]
        layout2 = _chunk_layout(metas2)
        assert [b - a for (_bi, a, b) in layout2] == [253, 253, 253, 251]
        assert layout2[-1][2] == 1010
        # sub-threshold bucket stays one piece alongside a chunked one
        metas3 = [(f32, [0], [(16,)], [f32]), (f32, [1], [(1024,)], [f32])]
        layout3 = _chunk_layout(metas3)
        assert layout3[0] == (0, 0, 16) and len(layout3) == 5
        # everything sub-threshold -> no chunked plan at all
        assert _chunk_layout([(f32, [0], [(16,)], [f32])]) is None
        # executor off -> chunking off
        os.environ["HVD_MAX_INFLIGHT_FLUSHES"] = "1"
        assert _chunk_layout(metas) is None
    finally:
        for k in ("HVD_PIPELINE_THRESHOLD", "HVD_PIPELINE_CHUNKS",
                  "HVD_MAX_INFLIGHT_FLUSHES"):
            os.environ.pop(k, None)


def test_chunked_plan_numerics_match_unchunked(hvd, monkeypatch):
    """Chunked wire pipeline vs the monolithic wire program: identical
    results, sync and async, plan cache serving both variants under
    distinct keys."""
    elems = 64 * 1024  # 256 KiB/tensor
    tensors = [hvd.per_rank([jnp.full((elems,), float((r + 1) * (i + 1)),
                                      jnp.float32) for r in range(N)])
               for i in range(2)]
    ref = [np.asarray(o)
           for o in hvd.grouped_allreduce(tensors, op=hvd.Sum)]
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    monkeypatch.setenv("HVD_PIPELINE_THRESHOLD", str(128 * 1024))
    monkeypatch.setenv("HVD_PIPELINE_CHUNKS", "4")
    before = dispatch_cache.stats()["chunked_builds"]
    outs = hvd.grouped_allreduce(tensors, op=hvd.Sum)
    assert dispatch_cache.stats()["chunked_builds"] == before + 1
    for r, o in zip(ref, outs):
        np.testing.assert_allclose(r, np.asarray(o))
    # steady state: second call is a plan HIT on the chunked plan
    h0 = dispatch_cache.stats()["hits"]
    outs2 = hvd.grouped_allreduce(tensors, op=hvd.Sum)
    assert dispatch_cache.stats()["hits"] == h0 + 1
    for r, o in zip(ref, outs2):
        np.testing.assert_allclose(r, np.asarray(o))
    # and through the queue (async flush -> chunked plan)
    hs = [hvd.allreduce_async(t, op=hvd.Sum) for t in tensors]
    for r, h in zip(ref, hs):
        np.testing.assert_allclose(r, np.asarray(hvd.synchronize(h)))


def test_pingpong_recycling_numerics(hvd, monkeypatch):
    """HVD_PIPELINE_PINGPONG=1 (forced on CPU, where 'auto' is off):
    repeated same-signature flushes rotate recycled scratch sets; every
    flush's numerics must stay exact — a corrupted scratch (result
    aliasing the reused buffer) would show up immediately."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    monkeypatch.setenv("HVD_PIPELINE_THRESHOLD", str(64 * 1024))
    monkeypatch.setenv("HVD_PIPELINE_CHUNKS", "2")
    monkeypatch.setenv("HVD_PIPELINE_PINGPONG", "1")
    elems = 32 * 1024
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # CPU: donation unsupported warns
        for step in range(1, 6):
            t = hvd.per_rank([jnp.full((elems,), float((r + 1) * step),
                                       jnp.float32) for r in range(N)])
            out, = hvd.grouped_allreduce([t], op=hvd.Sum)
            np.testing.assert_allclose(
                np.asarray(out), np.full((elems,), 36.0 * step))


# ------------------------------------------------- threaded stress (satellite)

def test_threaded_producers_fifo_and_numerics(hvd, monkeypatch):
    """N producer threads enqueue mixed allreduce_async/broadcast_async
    while the cycle timer fires: per-signature FIFO order (each
    producer's submissions appear in its submission order in the
    concatenated flush compositions), numerics equal to the analytic
    scheduler-off results, and no deadlock."""
    monkeypatch.setenv("HVD_CYCLE_TIME", "5")  # timer fires mid-stream
    monkeypatch.setenv("HVD_PENDING_CYCLE_TIME", "5")
    monkeypatch.setenv("HVD_FUSION_THRESHOLD", "400")
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    fusion_cycle.reset()
    sched = fusion_cycle.scheduler()
    sched.flush_history = type(sched.flush_history)(maxlen=4096)

    n_threads, per_thread = 4, 12
    results: dict = {}
    errors: list = []

    def producer(tid):
        try:
            hs = []
            for i in range(per_thread):
                if i % 4 == 3:
                    h = hvd.broadcast_async(
                        hvd.per_rank(_vals(mult=tid + i + 1)), 0,
                        name=f"b{tid}.{i:02d}")
                    hs.append((i, "bcast", tid + i + 1, h))
                else:
                    h = hvd.allreduce_async(
                        hvd.per_rank(_vals(mult=tid * 100 + i + 1)),
                        op=hvd.Sum, name=f"a{tid}.{i:02d}")
                    hs.append((i, "sum", tid * 100 + i + 1, h))
            results[tid] = [(i, kind, mult, hvd.synchronize(h))
                            for i, kind, mult, h in hs]
        except BaseException as exc:  # pragma: no cover - surfaced below
            errors.append((tid, exc))

    threads = [threading.Thread(target=producer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "producer deadlocked"
    assert not errors, errors

    for tid, outs in results.items():
        for i, kind, mult, out in outs:
            if kind == "sum":
                np.testing.assert_allclose(np.asarray(out),
                                           _sum_expected(mult=mult))
            else:  # broadcast from rank 0: rank 0's value = 1 * mult
                np.testing.assert_allclose(np.asarray(out),
                                           np.full((8,), float(mult)))

    # per-signature FIFO: within each queue, each producer's names appear
    # in submission order across the concatenated flush compositions
    history = list(sched.flush_history)
    for prefix in ("a", "b"):
        for tid in range(n_threads):
            seen = [n for (_t, _k, names) in history for n in names
                    if n.startswith(f"{prefix}{tid}.")]
            assert seen == sorted(seen), (prefix, tid, seen)
            expected = per_thread // 4 if prefix == "b" \
                else per_thread - per_thread // 4
            assert len(seen) == expected


def test_abort_mid_pipeline_no_deadlock(hvd, monkeypatch):
    """abort() while producers are submitting and the executor is
    dispatching: every handle must resolve (result or error) within a
    bounded wait — aborted entries raise at synchronize, in-flight ones
    deliver; nothing hangs."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    monkeypatch.setenv("HVD_FUSION_THRESHOLD", "200")
    fusion_cycle.reset()
    handles: list = []
    hmu = threading.Lock()
    stop = threading.Event()

    def producer():
        i = 0
        while not stop.is_set() and i < 60:
            h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
            with hmu:
                handles.append(h)
            i += 1

    threads = [threading.Thread(target=producer) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    aborted = fusion_cycle.scheduler().abort("mid-pipeline abort test")
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "producer deadlocked after abort"
    delivered = failed = 0
    deadline = time.monotonic() + 30
    with hmu:
        snapshot = list(handles)
    for h in snapshot:
        while not hvd.poll(h):
            assert time.monotonic() < deadline, "handle never resolved"
            time.sleep(0.01)
        try:
            out = hvd.synchronize(h)
            np.testing.assert_allclose(np.asarray(out), _sum_expected())
            delivered += 1
        except RuntimeError as e:
            assert "abort" in str(e)
            failed += 1
    assert delivered + failed == len(snapshot)
    assert aborted >= 0  # abort count is whatever was still queued
    # the scheduler stays usable after the abort
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected())


# ------------------------------------------- overlap metrics (stub device)

class _StubArray:
    """Deterministic device-completion stand-in: ``is_ready`` flips on
    command, ``block_until_ready`` (what slot admission calls through
    ``jax.block_until_ready``) waits for it. ``wait_entered`` observes the
    executor blocking on THIS array — releasing only after that makes the
    depth sample deterministic (sampling precedes blocking)."""

    def __init__(self):
        self._ready = threading.Event()
        self.wait_entered = threading.Event()

    def is_ready(self):
        return self._ready.is_set()

    def block_until_ready(self):
        self.wait_entered.set()
        assert self._ready.wait(30.0), "stub never released"
        return self

    def release(self):
        self._ready.set()


def test_stub_device_overlap_metrics(monkeypatch):
    """ISSUE 6 acceptance: with 2 slots and device completion controlled
    by hand, dispatch-time depth must reach 2 (two earlier flushes in
    flight when the third dispatches), overlap_ratio must be > 0, and
    slot blocking must accumulate device_wait_ms. The pre-fix accounting
    sampled depth AFTER eager retirement and slot blocking, which could
    never observe the full window."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    sched = fusion_cycle.FusionScheduler()
    stubs = [_StubArray() for _ in range(3)]

    def fake_execute(spec, entries, ticket=None):
        for e in entries:
            e.results = [stubs[int(e.label)]]
            e.tensors = ()
            e.event.set()

    sched._execute = fake_execute
    spec = fusion_cycle._QueueSpec("allreduce", None, None)
    entries = [fusion_cycle._Entry([None], False, 8, [str(i)])
               for i in range(3)]
    try:
        for e in entries:
            sched._submit(fusion_cycle._Batch(spec, [e], "threshold"))
        # batches 0 and 1 dispatch without blocking (window not full);
        # batch 2's admission samples depth 2 (stubs 0 and 1 both
        # unready), then blocks on the OLDEST in-flight stub
        assert stubs[0].wait_entered.wait(10.0), \
            "executor never blocked on the full window"
        time.sleep(0.02)  # measurable device_wait_ms
        stubs[0].release()
        # stub 1 stays unready until batch 2 has dispatched (quiesce
        # returns after the batch completes): its post-blocking overlap
        # sample must deterministically see one live predecessor
        sched.quiesce()
        for s in stubs[1:]:
            s.release()
        p = sched.stats()["pipeline"]
        assert p["executed"] == 3
        assert p["inflight_peak"] == 2, p
        assert p["overlap_ratio"] == pytest.approx(2.0 / 3.0), p
        assert p["slot_waits"] == 1, p
        assert p["device_wait_ms"] > 0.0, p
    finally:
        for s in stubs:
            s.release()
        sched.stop()


def test_stub_device_slots1_reports_zero_overlap(monkeypatch):
    """slots=1 is the documented synchronous mode: every dispatch waits
    out its predecessor at slot admission, so overlap_ratio must read
    0.0 — the overlap sample is post-blocking — even though
    admission-time pressure (inflight_peak) sees each predecessor still
    in flight as the next batch arrives."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "1")
    sched = fusion_cycle.FusionScheduler()
    stubs = [_StubArray() for _ in range(3)]

    def fake_execute(spec, entries, ticket=None):
        for e in entries:
            e.results = [stubs[int(e.label)]]
            e.tensors = ()
            e.event.set()

    sched._execute = fake_execute

    def _release_when_blocked_on():
        for s in stubs[:2]:  # the third is never blocked on
            s.wait_entered.wait(10.0)
            s.release()

    releaser = threading.Thread(target=_release_when_blocked_on,
                                daemon=True)
    releaser.start()
    spec = fusion_cycle._QueueSpec("allreduce", None, None)
    try:
        for i in range(3):
            sched._submit(fusion_cycle._Batch(
                spec, [fusion_cycle._Entry([None], False, 8, [str(i)])],
                "threshold"))
        sched.quiesce()
        p = sched.stats()["pipeline"]
        assert p["executed"] == 3
        assert p["overlap_ratio"] == 0.0, p
        assert p["inflight_peak"] == 1, p
        assert p["slot_waits"] == 2, p
        assert p["device_wait_ms"] > 0.0, p
    finally:
        for s in stubs:
            s.release()
        sched.stop()
        releaser.join(timeout=10)


def test_stub_device_no_overlap_when_synchronous(monkeypatch):
    """Control for the stub test: a stream whose flushes complete before
    the next admission reports zero overlap — the metric cannot invent
    overlap that did not happen."""
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    sched = fusion_cycle.FusionScheduler()

    def fake_execute(spec, entries, ticket=None):
        for e in entries:
            stub = _StubArray()
            stub.release()  # device completes immediately
            e.results = [stub]
            e.tensors = ()
            e.event.set()

    sched._execute = fake_execute
    spec = fusion_cycle._QueueSpec("allreduce", None, None)
    try:
        for i in range(3):
            sched._submit(fusion_cycle._Batch(
                spec, [fusion_cycle._Entry([None], False, 8, [str(i)])],
                "threshold"))
        sched.quiesce()
        p = sched.stats()["pipeline"]
        assert p["executed"] == 3
        assert p["overlap_ratio"] == 0.0, p
        assert p["inflight_peak"] == 0, p
        assert p["device_wait_ms"] == 0.0, p
    finally:
        sched.stop()


# ------------------------------------------------------------------- stats

def test_fusion_stats_pipeline_fields(hvd):
    st = hvd.fusion_stats()
    p = st["pipeline"]
    for key in ("enabled", "max_inflight", "chunking", "submitted",
                "executed", "queue_depth", "overlap_ratio",
                "slot_occupancy", "inflight_peak", "slot_waits",
                "device_wait_ms"):
        assert key in p
    assert "wire_programs" in st


def test_overlap_ratio_counts_inflight_admissions(hvd, monkeypatch):
    monkeypatch.setenv("HVD_MAX_INFLIGHT_FLUSHES", "2")
    monkeypatch.setenv("HVD_FUSION_THRESHOLD", "100")
    for i in range(8):
        hvd.allreduce_async(hvd.per_rank(_vals(mult=i + 1)), op=hvd.Sum)
    fusion_cycle.scheduler().flush_all("barrier")
    p = hvd.fusion_stats()["pipeline"]
    assert p["executed"] >= 2
    assert 0.0 <= p["overlap_ratio"] <= 1.0
    assert 0.0 < p["slot_occupancy"] <= 1.0
