"""Recoverable-error classification (elastic retry vs user bug)."""

import pytest

from horovod_tpu.exceptions import (
    HorovodInternalError,
    is_recoverable_distributed_error,
    wrap_internal_errors,
)


class TestRecoverableClassification:
    def test_gloo_peer_loss_is_recoverable(self):
        # XLA-CPU surfaces a dead peer as a builtin ValueError.
        e = ValueError(
            "UNKNOWN: Gloo all-reduce failed: [gloo/transport/tcp/pair.cc] "
            "Connection closed by peer [127.0.0.1]:10148")
        assert is_recoverable_distributed_error(e)

    def test_coordination_service_error_is_recoverable(self):
        e = RuntimeError("coordination service heartbeat failure")
        assert is_recoverable_distributed_error(e)

    def test_user_http_503_is_not_recoverable(self):
        # Regression: broad single-word markers ("unavailable", "peer")
        # must not swallow ordinary user exceptions into the retry loop.
        e = RuntimeError("HTTP 503 service unavailable from storage backend")
        assert not is_recoverable_distributed_error(e)

    def test_user_value_error_is_not_recoverable(self):
        e = ValueError("peer review of distributed dataset failed")
        assert not is_recoverable_distributed_error(e)

    def test_jax_typed_errors_use_broad_markers(self):
        class FakeXlaError(Exception):
            pass
        FakeXlaError.__module__ = "jaxlib.xla_extension"
        assert is_recoverable_distributed_error(
            FakeXlaError("collective operation deadline exceeded"))

    def test_wrap_translates_recoverable(self):
        @wrap_internal_errors
        def boom():
            raise ValueError("Gloo all-gather failed: Connection reset by peer")
        with pytest.raises(HorovodInternalError):
            boom()

    def test_wrap_passes_user_errors(self):
        @wrap_internal_errors
        def boom():
            raise KeyError("missing config key")
        with pytest.raises(KeyError):
            boom()
