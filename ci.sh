#!/usr/bin/env bash
# CI gate: the checks a snapshot must pass before it ships.
#
# Mirrors the reference's pipeline structure (.buildkite/gen-pipeline.sh:
# unit suite + parallel multi-process jobs + example smoke runs), adapted to
# the TPU-native rebuild: everything runs on a virtual 8-device CPU mesh so
# no cluster (and no TPU) is required.
#
# Usage: ./ci.sh            # full gate
#        ./ci.sh --fast     # suite only (skip artifacts + examples)
set -euo pipefail
cd "$(dirname "$0")"

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

fail=0

step() { echo; echo "=== $* ==="; }

step "0/6 native build from source (no committed binaries)"
python -c "from horovod_tpu._native import build_native; print(build_native(force=True))"

step "1/6 test suite (tests/, virtual 8-device mesh via conftest)"
python -m pytest tests/ -q -x

if [[ "${1:-}" == "--fast" ]]; then
  step "fast: examples/mnist.py (hvdrun -np 2) then exit"
  env -u XLA_FLAGS python -m horovod_tpu.runner.launch -np 2 -- \
    python examples/mnist.py --smoke
  echo "--fast: skipping second suite pass + artifact + full example checks"
  exit 0
fi

step "1b/6 test suite, second pass (flake detection)"
python -m pytest tests/ -q -x

step "2/6 driver artifact: single-chip compile check (entry)"
python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn).lower(*args).compile()
print("entry() compile OK")
EOF

step "3/6 driver artifact: multi-chip dryrun (8 virtual devices)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

step "4/6 example smoke runs (single-process 8-dev mesh + np=2 hvdrun, like gen-pipeline.sh:160-290)"
for ex in examples/*.py; do
  echo "--- $ex (1 process, 8 virtual devices)"
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python "$ex" --smoke || fail=1
done
echo "--- examples/mnist.py (hvdrun -np 2)"
env -u XLA_FLAGS python -m horovod_tpu.runner.launch -np 2 -- \
  python examples/mnist.py --smoke || fail=1

step "5/6 eager negotiation microbench (np=2, sanity: both paths work)"
env -u XLA_FLAGS python eager_bench.py --iters 40 --warmup 5 | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['adaptive_cycle']['ops_per_sec'] > 0, d
assert d['fixed_cycle']['ops_per_sec'] > 0, d
print('eager negotiation OK:', d['adaptive_cycle']['ms_per_negotiation'],
      'ms/negotiation adaptive vs', d['fixed_cycle']['ms_per_negotiation'],
      'fixed')" || fail=1

exit $fail
