#!/usr/bin/env bash
# CI gate: the checks a snapshot must pass before it ships.
#
# Mirrors the reference's pipeline structure (.buildkite/gen-pipeline.sh:
# unit suite + parallel multi-process jobs + example smoke runs), adapted to
# the TPU-native rebuild: everything runs on a virtual 8-device CPU mesh so
# no cluster (and no TPU) is required.
#
# Usage: ./ci.sh            # full gate
#        ./ci.sh --fast     # suite only (skip artifacts + examples)
set -euo pipefail
cd "$(dirname "$0")"

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

fail=0

step() { echo; echo "=== $* ==="; }

step "0/6 native build from source (no committed binaries)"
python -c "from horovod_tpu._native import build_native; print(build_native(force=True))"

step "0b/6 native TSan lane (threaded engine under -fsanitize=thread; optional)"
# The native engine's real pthreads (timeline writer thread + the
# embedder's submitter/negotiator/watchdog threads) sit outside
# hvdsched's cooperative seam, so they get a ThreadSanitizer lane
# instead: native/tsan_harness.cc drives the documented hvd_core.h
# concurrency contract hard and asserts cross-rank response-list
# equality while it runs. Any data-race report fails the build. A
# toolchain without a working TSan runtime (probe below) skips with
# notice — the lane is additive coverage, not a portability gate.
CXX_BIN="${CXX:-g++}"
tsan_dir="$(mktemp -d)"
echo 'int main(){return 0;}' > "$tsan_dir/probe.cc"
if "$CXX_BIN" -fsanitize=thread -O1 -std=c++17 -pthread \
     "$tsan_dir/probe.cc" -o "$tsan_dir/probe" 2>/dev/null \
   && "$tsan_dir/probe" 2>/dev/null; then
  "$CXX_BIN" -fsanitize=thread -O1 -g -std=c++17 -pthread \
    native/tsan_harness.cc native/engine.cc native/timeline.cc \
    -o "$tsan_dir/tsan_harness"
  TSAN_OPTIONS="halt_on_error=1" \
    timeout -k 10 120 "$tsan_dir/tsan_harness" "$tsan_dir/timeline.json"
else
  echo "tsan lane: skipped (toolchain lacks a working -fsanitize=thread runtime)"
fi
rm -rf "$tsan_dir"

step "0a/6 hvdlint static analysis gate (project invariants; docs/static_analysis.md)"
# AST-only, no jax import: the cheapest gate runs first. The --json
# report carries file/line/pass/message records plus per-pass timing;
# findings surface as structured CI annotations. Any finding
# (issue-lock / lock-order / timer-purity / knob-registry / donation /
# silent-except / rank-divergence / metrics-registry / trace-coverage)
# fails the build. --root tools lints the checkers themselves with the
# same passes (registry round-trips no-op there; CLI-layer knob reads
# and best-effort excepts carry justified pragmas).
lint_rc=0
lint_json="$(mktemp)"
python -m tools.hvdlint horovod_tpu --root tools --json > "$lint_json" || lint_rc=$?
# rc 0/1 = a report was emitted (clean/findings); anything else is an
# abnormal exit (usage error, crash) whose stderr is the real signal —
# don't bury it under a JSONDecodeError from an empty report file
if [ "$lint_rc" -le 1 ]; then
  LINT_JSON="$lint_json" python - <<'EOF'
import json, os
d = json.load(open(os.environ["LINT_JSON"]))
for f in d["findings"]:
    print("::error file=%s,line=%d,title=hvdlint/%s::%s"
          % (f["file"], f["line"], f["pass"], f["message"]))
timing = ", ".join("%s %.0fms" % (p["name"], p["seconds"] * 1e3)
                   for p in d["passes"])
state = "clean" if d["clean"] else "%d finding(s)" % len(d["findings"])
print("hvdlint: %s across %d files (%s)" % (state, d["files"], timing))
EOF
fi
rm -f "$lint_json"
[ "$lint_rc" -eq 0 ]

# Pass-count floor for the tier-1 gate. The 13 multi-process spawn tests
# that fail on jax builds whose CPU backend lacks cross-process
# computations ("Multiprocess computations aren't implemented on the CPU
# backend") are now SKIPPED via tests/backend_markers.py, so the dot
# count is a clean signal. Raise this when the environment's pass level
# rises; override with T1_MIN_PASSED.
T1_MIN_PASSED="${T1_MIN_PASSED:-773}"

step "1/6 tier-1 gate (the ROADMAP.md command; floor: $T1_MIN_PASSED passed)"
# faulthandler_timeout: a hung test (e.g. a flush-executor deadlock) dumps
# every thread's stack after 300 s instead of silently burning the 870 s
# budget — the dump lands in the log while the timeout still enforces.
( set +e; set -o pipefail; rm -f /tmp/_t1.log; \
  timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    -o faulthandler_timeout=300 \
    2>&1 | tee /tmp/_t1.log; \
  dots=$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); \
  echo "DOTS_PASSED=$dots (floor $T1_MIN_PASSED)"; \
  [ "$dots" -ge "$T1_MIN_PASSED" ] )

step "1a/6 dispatch-overhead microbench (plan cache must hold its steady-state win)"
python bench.py --dispatch-bench --dispatch-iters 200 | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] >= 30.0, \
    'plan cache lost its steady-state win: %r' % d
print('dispatch bench OK: %.1f%% per-call reduction (%.3f -> %.3f ms)' % (
    d['value'], d['cache_off']['ms_per_call'], d['cache_on']['ms_per_call']))"

step "1c/6 cycle-fusion microbench (the cross-call scheduler must hold its coalescing win)"
# ABBA-interleaved on/off chunks (ISSUE 12 satellite): the old
# sequential two-block comparison read 10-16% against a 40% absolute
# floor on slower boxes even at baseline — box drift between the blocks
# swamped the scheduler's own delta, and the absolute win is genuinely
# box-dependent (dispatch overhead vs XLA execution ratio). The
# interleave makes the number stable run-to-run (+/- ~1 point
# observed); the floor is 10% wall-clock win on any box plus the
# box-independent mechanism signal, the coalescing ratio. Override with
# CYCLE_MIN_REDUCTION on known-fast boxes.
CYCLE_MIN_REDUCTION="${CYCLE_MIN_REDUCTION:-10.0}"
python bench.py --cycle-bench --cycle-iters 30 | CYCLE_MIN_REDUCTION="$CYCLE_MIN_REDUCTION" python -c "
import json, os, sys
d = json.loads(sys.stdin.readlines()[-1])
floor = float(os.environ['CYCLE_MIN_REDUCTION'])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] >= floor, \
    'fusion scheduler lost its per-tensor win (floor %.1f%%): %r' % (floor, d)
assert d['coalesce_ratio'] > 8.0, \
    'fusion scheduler stopped coalescing: %r' % d
print('cycle bench OK: %.1f%% per-tensor reduction (%.3f -> %.3f ms), '
      'coalesce %.1fx' % (d['value'], d['scheduler_off']['ms_per_tensor'],
                          d['scheduler_on']['ms_per_tensor'],
                          d['coalesce_ratio']))"

step "1d/6 pipelined-flush microbench (executor + chunk pipeline must hold their large-tensor win)"
python bench.py --pipeline-bench --pipeline-iters 12 | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] >= 20.0, \
    'pipelined flush executor lost its large-tensor win: %r' % d
print('pipeline bench OK: %.1f%% wall-time reduction (%.1f -> %.1f ms/round)'
      % (d['value'], d['synchronous']['ms_per_round'],
         d['pipelined']['ms_per_round']))"

step "1g/6 flush-overlap microbench (the executor must actually hold two flushes in flight)"
python bench.py --overlap-bench --overlap-iters 8 | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] > 0.0, \
    'pipelined executor shows zero flush overlap with >=2 slots: %r' % d
p = d['pipelined']['pipeline']
assert p['executed'] >= 2, d
print('overlap bench OK: overlap_ratio %.2f (peak depth %d, '
      'device_wait %.1f ms, %.1f%% wall-time reduction)' % (
          d['value'], p['inflight_peak'], p['device_wait_ms'],
          d['wall_time_reduction_pct']))"

step "1i/6 bucketed step bench (bucketed backward must not be slower than whole-tree)"
# End-to-end eager DP step time, models/ ResNet-50: HVD_BUCKET_BYTES
# bucketing vs the whole-tree grouped allreduce. Hard gates: numerics
# parity, nonzero overlap ratio, and bucketed gradient-sync latency no
# slower than whole-tree + 5% (the mechanism's direct measurement on
# the model's real grad tree; 7-sample medians on a loaded box still
# jitter a few percent). The chained step-time gate allows 10% jitter
# because the CI box is a 2-core CPU emulating 8 chips — comm and
# compute fully contend there, so the chained wall clock carries that
# much run-to-run noise (see BENCH_r10.json). Up to two retries in a
# FRESH process each: per-process scheduling luck at warmup can put
# two in-flight chunked collectives into a contended schedule that
# slows every bucketed step of that process ~1.5-2x while whole-tree
# mode in the same run is unaffected (~1 in 4 runs observed; see
# docs/pipeline.md "CPU-emulation caveat") — a re-roll clears
# scheduling luck, while a real regression fails every attempt.
step_bench_gate() {
python bench.py --step-bench --step-iters 5 --step-batch 1 \
    --step-bucket-bytes 16777216 > /tmp/hvd_step_bench.out \
  && python -c "
import json
d = json.loads(open('/tmp/hvd_step_bench.out').readlines()[-1])
assert d['numerics_match'] is True, d
r = d['models']['resnet50']
assert r['grad_sync_bucketed_ms'] <= r['grad_sync_whole_ms'] * 1.05, \
    'bucketed gradient sync slower than whole-tree beyond CI noise: %r' % r
assert r['bucketed_ms_per_step'] <= r['whole_tree_ms_per_step'] * 1.10, \
    'bucketed backward slower than whole-tree beyond CI noise: %r' % r
assert r['pipeline_overlap']['overlap_ratio'] > 0.0, \
    'bucketed backward shows zero comm overlap: %r' % r
# ISSUE-16 GSPMD lane: cached replay at least halves the
# retrace-per-call step, with zero retraces, hits attributed to the
# gspmd source, and numerics matching both the uncached GSPMD step and
# the eager-DP lane
g = d['models']['gspmd']
assert g['numerics_match'] is True, g
assert g['warm_retraces'] == 0, \
    'gspmd cached replay retraced: %r' % g
assert g['cache_hits'] >= 1, \
    'gspmd lane registered no dispatch-cache hits: %r' % g
assert g['reduction_pct'] >= 50.0, \
    'gspmd cached replay under 50%% step-time reduction: %r' % g
print('step bench OK: resnet50 step %.0f -> %.0f ms (%.1f%%), grad sync '
      '%.0f -> %.0f ms (%.1f%%), overlap_ratio %.2f, %d buckets' % (
          r['whole_tree_ms_per_step'], r['bucketed_ms_per_step'],
          r['reduction_pct'], r['grad_sync_whole_ms'],
          r['grad_sync_bucketed_ms'], r['grad_sync_reduction_pct'],
          r['pipeline_overlap']['overlap_ratio'], r['buckets']))
print('gspmd lane OK: %.0f -> %.0f ms warm (%.1f%%), %d cache hits' % (
    g['uncached_ms_per_step'], g['cached_warm_ms_per_step'],
    g['reduction_pct'], g['cache_hits']))"
}
step_bench_gate || {
  echo "step bench attempt 1 failed; retrying in a fresh process"
  step_bench_gate || {
    echo "step bench attempt 2 failed; final retry in a fresh process"
    step_bench_gate
  }
}
# both execution modes (eager-DP bucketing + GSPMD cached program) on one
# perf trajectory; the passing run's artifact is BENCH_r16.json
tail -1 /tmp/hvd_step_bench.out > BENCH_r16.json

step "1m/6 metrics scrape gate (loopback world=4 /metrics completeness; docs/metrics.md)"
# ISSUE-11 acceptance: a curl-able /metrics on the loopback world's KV
# server exposes EVERY registered instrument (HELP/TYPE headers even
# before first sample), every sample line parses, and the load-bearing
# series are live at world=4: negotiation round latency, per-rank submit
# lag, KV ops, and per-tenant fusion counters. A fault-injected slow
# rank must be named in the straggler counter's labels.
env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    HVD_FAULT_SPEC="svc.exchange:delay=0.4:rank=2:after=4" \
    timeout -k 10 300 python - <<'EOF'
import urllib.request
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu import metrics as m

with hvd.loopback.world(4, extra_env={"HVD_STRAGGLER_THRESHOLD": "0.15"}) as w:
    def body():
        for i in range(8):
            hvd.allreduce(jnp.ones(4), op=hvd.Sum, name=f"g{i}")
        # async: rides the fusion queues, so the per-tenant flush
        # counters are live series, not just registered headers
        h = hvd.allreduce_async(jnp.ones(8), op=hvd.Sum, name="ga")
        hvd.synchronize(h)
        return "OK"
    assert all(o.result == "OK" for o in w.run(body))
    addr, port = w.kv_endpoint
    text = urllib.request.urlopen(
        f"http://{addr}:{port}/metrics", timeout=30).read().decode()

for name, inst in sorted(m.instruments().items()):
    assert f"# HELP {name} " in text, f"missing HELP for {name}"
    assert f"# TYPE {name} {inst.kind}" in text, f"missing TYPE for {name}"
samples = [l for l in text.splitlines() if l and not l.startswith("#")]
for line in samples:
    name_part, _, value = line.rpartition(" ")
    float(value)  # every sample parses
    assert name_part.split("{")[0].startswith("hvd_"), line
assert len(samples) == len(set(samples)), "duplicate series in exposition"
def series(prefix):
    return [l for l in samples if l.startswith(prefix)]
for r in range(4):
    assert series(f'hvd_negotiation_rounds_total{{process_set="global",rank="{r}"}}'), r
assert series("hvd_negotiation_round_seconds_count"), "no round latency"
assert series("hvd_negotiation_submit_lag_seconds_count"), "no submit lag"
assert series("hvd_kv_ops_total"), "no KV op counters"
assert series('hvd_fusion_flushed_tensors_total{process_set="global"'), \
    "no per-tenant fusion counters"
strag = series('hvd_straggler_rounds_total{rank="2"')
assert strag, "fault-injected slow rank 2 not named in straggler counter"
print(f"metrics scrape OK: {len(samples)} samples, "
      f"{len(m.instruments())} instruments, straggler series: {strag}")
EOF

step "1n/6 metrics overhead gate (HVD_METRICS=1 within 3% of off; docs/metrics.md)"
# The registry's hot instruments ride the per-call dispatch path; the
# interleaved ABBA microbench keeps box drift out of the comparison.
# Same fresh-process retry policy as 1i: sub-3% deltas on the 2-core
# CPU emulation carry scheduling luck; a real regression fails every
# attempt.
metrics_bench_gate() {
python bench.py --metrics-bench | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] <= 3.0, \
    'metrics registry overhead beyond the 3%% contract: %r' % d
print('metrics overhead OK: %.2f%% (%.4f -> %.4f ms/tensor)' % (
    d['value'], d['metrics_off']['ms_per_tensor'],
    d['metrics_on']['ms_per_tensor']))"
}
metrics_bench_gate || {
  echo "metrics bench attempt 1 failed; retrying in a fresh process"
  metrics_bench_gate || {
    echo "metrics bench attempt 2 failed; final retry in a fresh process"
    metrics_bench_gate
  }
}

step "1t/6 conformance overhead gate (HVD_CONFORMANCE=1 within 3% of off; docs/conformance.md)"
# The lockstep recorder's hooks ride the same hot dispatch path as the
# metrics instruments; the interleaved ABBA microbench keeps box drift
# out of the comparison, and the gate also demands the enabled pass
# actually recorded flush events (a silently-dead recorder would read
# as 0% overhead AND zero coverage). Same fresh-process retry policy as
# 1n: sub-3% deltas on the 2-core CPU emulation carry scheduling luck.
conformance_bench_gate() {
python bench.py --conformance-bench | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] <= 3.0, \
    'conformance recorder overhead beyond the 3%% contract: %r' % d
assert d['conformance_on']['by_stream']['flush'] > 0, \
    'enabled recorder saw no flush events (dead hooks): %r' % d
print('conformance overhead OK: %.2f%% (%.4f -> %.4f ms/tensor), '
      '%d events recorded' % (
    d['value'], d['conformance_off']['ms_per_tensor'],
    d['conformance_on']['ms_per_tensor'], d['conformance_on']['events']))"
}
conformance_bench_gate || {
  echo "conformance bench attempt 1 failed; retrying in a fresh process"
  conformance_bench_gate || {
    echo "conformance bench attempt 2 failed; final retry in a fresh process"
    conformance_bench_gate
  }
}

step "1j/6 schedule-exploration gate (hvdsched race matrix; docs/schedule_checker.md)"
# Controlled-concurrency model checking of the fusion scheduler x flush
# executor x abort x watchdog x quiesce race matrix — now including the
# multi-tenant QoS admission model (enqueue x weighted admission x shed
# quota racing abort; ISSUE 12) — with zero deadlock/lost-wakeup/
# livelock findings allowed. Then detector sanity: the known-bad
# fixtures (lock inversion, missed signal, unguarded PR-3/PR-6 shapes,
# the planted QoS priority-inversion) must all be FOUND. Wall-clock
# capped; any finding dumps its (seed, trace) replay line.
# budgets scale with the registries: 13 matrix models x 24, 10 demos x 22
# (ISSUE 13 added hier-negotiation + leader-lost-wakeup; ISSUE 14 added
# elastic-reform + stale-plan-after-resize-demo; ISSUE 15 added
# autoscale-decision (round-tagged policy apply racing a watchdog
# re-form and a commit waiter) + the planted evict-during-reform-demo;
# the state plane adds ckpt-snapshot (snapshot writer racing commits
# and teardown; docs/checkpoint.md) + the planted
# stale-manifest-restore-demo (pointer read without a generation
# re-check against the manifest write)).
# The matrix runs --json and a starvation gate reads the per-model
# accounting: explore() drives every clean model to its ceil-split
# budget, so runs < SCHED_MODEL_FLOOR means the registry outgrew
# --schedules and models are silently under-explored — raise the
# budget, don't shave the floor. Findings still print their (seed,
# trace) replay lines on stderr in --json mode.
SCHED_MODEL_FLOOR="${SCHED_MODEL_FLOOR:-16}"
sched_rc=0
HVD_SCHED_CHECK=1 timeout -k 10 300 python -m tools.hvdsched --schedules 320 --json \
  > /tmp/hvd_sched_matrix.json || sched_rc=$?
# rc 0/1 = a report was emitted; anything else (timeout, crash) has its
# real signal on stderr — don't bury it under a JSONDecodeError
if [ "$sched_rc" -le 1 ]; then
  SCHED_MODEL_FLOOR="$SCHED_MODEL_FLOOR" python - <<'EOF'
import json, os
d = json.load(open("/tmp/hvd_sched_matrix.json"))
floor = int(os.environ["SCHED_MODEL_FLOOR"])
bad = [r["model"] for r in d["results"] if r["findings"]]
assert d["clean"] and not bad, "matrix findings in %r (replay on stderr)" % bad
starved = [(r["model"], r["runs"]) for r in d["results"]
           if r["runs"] < floor]
assert not starved, (
    "budget ceil-split starved model(s) under the %d-schedule floor: %r"
    " — the model registry outgrew --schedules 320" % (floor, starved))
print("sched matrix OK: %d models x %d schedules (floor %d), "
      "%d branched, %d pruned as equivalent, %d seed-swept" % (
          d["models"], d["per_model"], floor,
          sum(r["branch_points"] for r in d["results"]),
          sum(r["pruned"] for r in d["results"]),
          sum(r["swept"] for r in d["results"])))
EOF
fi
[ "$sched_rc" -eq 0 ]
HVD_SCHED_CHECK=1 timeout -k 10 300 python -m tools.hvdsched --demos --schedules 220

step "1l/6 loopback chaos gate (world=4 rank death under HVD_DEBUG_INVARIANTS=1; docs/loopback.md)"
# The loopback world's failure-domain acceptance (ISSUE 10): an
# HVD_FAULT_SPEC rank death at world=4 must surface PeerFailureError on
# every survivor in < 5 s (watchdog silence detection over the shared
# KV), and a mid-elastic-run death must drive blacklist + re-form to a
# completed job. Runs with the concurrency witness on: a coordinated
# abort that corrupts lock order across the rank threads fails here.
env HVD_DEBUG_INVARIANTS=1 timeout -k 10 600 \
  python -m pytest tests/test_loopback_world.py::TestChaos -q \
    -o faulthandler_timeout=300

step "1k/6 step capture-and-replay bench (whole-step replay must beat the per-flush path)"
# End-to-end eager DP transformer step: HVD_STEP_CAPTURE on (step 1
# records the flush stream, later steps replay ONE cached jitted
# program) vs off (the per-flush dispatch path). Hard gates: >=25%
# step-time reduction, numerics identical capture on/off, steps
# actually replayed, and the forced mid-run divergence (bucket layout
# flip) fell back to eager with correct results — no hang, no
# stale-plan reuse. Same fresh-process retry policy as step 1i: the
# 2-core CPU emulation's process-sticky scheduling luck swings both
# sides of this bench (docs/pipeline.md "CPU-emulation caveat"); a
# re-roll clears luck, a real regression fails every attempt.
capture_bench_gate() {
python bench.py --capture-bench | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] >= 25.0, \
    'step capture lost its replay win: %r' % d
assert min(d['replayed_steps_by_pass']) > 0, \
    'a capture pass never replayed: %r' % d
assert min(d['divergence']['fallbacks_by_pass']) >= 1, \
    'forced divergence never fell back in some pass: %r' % d
assert d['divergence']['numerics_match'] is True, d
print('capture bench OK: %.1f%% step-time reduction (%.0f -> %.0f ms), '
      '%d replays, %d divergence fallback(s)' % (
          d['value'], d['eager']['ms_per_step'],
          d['captured']['ms_per_step'], d['replayed_steps'],
          d['divergence']['fallbacks']))"
}
capture_bench_gate || {
  echo "capture bench attempt 1 failed; retrying in a fresh process"
  capture_bench_gate || {
    echo "capture bench attempt 2 failed; final retry in a fresh process"
    capture_bench_gate
  }
}

step "1o/6 serve-bench QoS gate (multi-tenant tail-latency protection; docs/qos.md)"
# ISSUE 12 acceptance: with HVD_QOS=1, the high-priority serve tenant's
# p99 per-request grad-sync latency stays <= SERVE_P99_MULT x its
# unloaded p99 while the bulk tenant saturates the engine past
# HVD_FUSION_MAX_PENDING (backpressure flushes observed), the bulk
# tenant's shed quota fires (QosAdmissionError on the handle), and the
# hvd_qos_* admission-wait/shed/slot-share series are live in the
# Prometheus scrape. Same fresh-process retry policy as steps 1i/1k:
# tail percentiles on the 2-core CPU emulation carry scheduling luck; a
# real regression fails every attempt.
SERVE_P99_MULT="${SERVE_P99_MULT:-2.0}"
serve_bench_gate() {
python bench.py --serve-bench | SERVE_P99_MULT="$SERVE_P99_MULT" python -c "
import json, os, sys
d = json.loads(sys.stdin.readlines()[-1])
mult = float(os.environ['SERVE_P99_MULT'])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] <= mult, \
    'high-priority p99 not protected under bulk load (cap %.1fx): %r' % (mult, d)
assert d['qos_on']['shed_total'] >= 1, 'bulk shed quota never fired: %r' % d
assert d['backpressure_flushes'] >= 1, \
    'bulk tenant never drove the engine past HVD_FUSION_MAX_PENDING: %r' % d
assert d['qos_series_in_scrape'] is True, \
    'hvd_qos_* series missing from the Prometheus scrape: %r' % d
print('serve bench OK: p99 %.1f -> %.1f ms under load (%.2fx of unloaded; '
      'cap %.1fx), QoS off %.2fx, %d sheds, %d backpressure flushes' % (
          d['qos_on']['unloaded_ms']['p99'], d['qos_on']['loaded_ms']['p99'],
          d['value'], mult, d['qos_off']['p99_protection_ratio'],
          d['qos_on']['shed_total'], d['backpressure_flushes']))"
}
serve_bench_gate || {
  echo "serve bench attempt 1 failed; retrying in a fresh process"
  serve_bench_gate || {
    echo "serve bench attempt 2 failed; final retry in a fresh process"
    serve_bench_gate
  }
}

step "1p/6 protocol-scalability gate (hierarchical negotiation + ResponseCache; docs/negotiation.md)"
# ISSUE 13 acceptance at CI scale (worlds 4+16; the BENCH_r13 artifact
# adds world=64): with HVD_RESPONSE_CACHE=1 + hierarchy on, steady-state
# negotiation runs ZERO busy KV rounds at every world (hit rate ~100%
# after warm-up, per-rank KV traffic flat in world — the idle heartbeat
# only), and the cached step-time growth world=4 -> world=16 stays far
# under the flat protocol's blowup (measured here: flat round latency
# grows ~100x over that span; the gate allows 4x for the cached lane).
# Fresh-process retries like steps 1i/1k: a share-throttled box can
# smear the per-step medians.
protocol_bench_gate() {
python bench.py --protocol-bench --protocol-worlds 4,16 --protocol-steps 6 | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['numerics_match'] is True, d
assert d['value'] is not None and d['value'] <= 1.5, \
    'cached per-rank KV ops/step grew with world: %r' % d
worlds = d['worlds']
for w, modes in worlds.items():
    c = modes['cached']
    assert c['busy_rounds_per_rank_step'] == 0.0, \
        'steady-state rounds not served from cache at world %s: %r' % (w, c)
    assert c['cache_hit_rate'] is not None and c['cache_hit_rate'] >= 0.95, \
        'cache hit rate below 95%% at world %s: %r' % (w, c)
lo, hi = sorted(worlds, key=int)[0], sorted(worlds, key=int)[-1]
ratio = worlds[hi]['cached']['steady_ms_per_step'] / \
    max(worlds[lo]['cached']['steady_ms_per_step'], 1e-9)
assert ratio < 4.0, \
    'cached step time grew %.1fx from world %s to %s (cap 4x)' % (ratio, lo, hi)
flat = {w: m['flat']['round_latency_ms_mean']
        for w, m in worlds.items() if 'flat' in m}
print('protocol bench OK: cached KV-ops growth %.2fx, step-time growth '
      '%.1fx (world %s -> %s), hit rates %s; flat round latency %s ms'
      % (d['value'], ratio, lo, hi,
         {w: m['cached']['cache_hit_rate'] for w, m in worlds.items()},
         flat))"
}
protocol_bench_gate || {
  echo "protocol bench attempt 1 failed; retrying in a fresh process"
  protocol_bench_gate || {
    echo "protocol bench attempt 2 failed; final retry in a fresh process"
    protocol_bench_gate
  }
}

step "1q/6 elastic-churn gate (scripted membership + warm re-form SLOs; docs/elastic.md)"
# ISSUE 14 acceptance at loopback world=4: a seeded HVD_FAULT_SPEC churn
# schedule (abrupt remove -> scale-up to a seen shape -> graceful
# preemption -> hard crash) must recover every event within budget,
# a preempt-with-grace must lose ZERO steps while the crash loses <=1,
# and the second 4->3 re-form (shape already shelved) must reuse cached
# plans (warm hits > 0) and run its first post-re-form window faster
# than the first, cold one. Fresh-process retries like steps 1i/1k —
# loopback rank threads time-slicing a share-throttled box can smear a
# single window. The passing run's artifact is BENCH_r14.json.
elastic_bench_gate() {
python bench.py --elastic-bench | tee /tmp/hvd_elastic_bench.out | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d.get('error') is None, d.get('error')
assert d['numerics_ok'] is True, d
warm, cold = d['warm_reform'], d['cold_reform']
assert warm and cold, 'warm/cold re-forms missing: %r' % d['events']
assert warm['warm_plan_reuses'] > 0, \
    'warm re-form reused no cached plans: %r' % d
assert warm['warm_response_confirms'] > 0, \
    'warm re-form did not re-arm the response cache: %r' % d
# The gated warm/cold metric is the DETERMINISTIC one: BUSY wire
# rounds spent over the identical post-re-form window (cold pays
# rounds per tensor until the caches re-arm; warm serves locally after
# the digest round — measured 0 vs 14-17 every run). Counts are immune
# to the box contention that swings the wall-clock step-time ratio
# 0.6x-1.8x run to run; that ratio rides along informationally as
# step_time_ratio.
wb, cb = warm.get('window_busy_rounds'), cold.get('window_busy_rounds')
assert wb is not None and cb is not None and wb < cb, \
    'warm window did not spend fewer wire rounds than cold: %r vs %r' \
    % (wb, cb)
assert d['value'] is not None and d['value'] < 1.0, \
    'warm/cold wire-round ratio not under 1: %r' % d['value']
assert warm['steps_lost'] == 0, \
    'preempt-with-grace lost steps: %r' % warm
crash = d['crash_reform']
assert crash and crash['steps_lost'] <= 1, \
    'crash lost more than one step: %r' % crash
assert d['recovery_s_max'] is not None and d['recovery_s_max'] < 45.0, \
    'recovery exceeded the 45 s budget: %r' % d
print('elastic bench OK: warm/cold wire rounds %d vs %d (ratio %s; '
      'step-time ratio %s informational), warm plan reuses %d, '
      'response re-arms %d, preempt lost %d, crash lost %d, worst '
      'recovery %.1fs over %d events' % (
          wb, cb, d['value'], d.get('step_time_ratio'),
          warm['warm_plan_reuses'], warm['warm_response_confirms'],
          warm['steps_lost'], crash['steps_lost'],
          d['recovery_s_max'], len(d['events'])))"
}
elastic_bench_gate || {
  echo "elastic bench attempt 1 failed; retrying in a fresh process"
  elastic_bench_gate || {
    echo "elastic bench attempt 2 failed; final retry in a fresh process"
    elastic_bench_gate
  }
}
tail -1 /tmp/hvd_elastic_bench.out > BENCH_r14.json

step "1r/6 autoscale gate (closed-loop SLO-driven add/remove/evict; docs/elastic.md 'Autoscaler')"
# ISSUE 15 acceptance: with HVD_AUTOSCALE=1 and NO script, a planted
# SLO breach must trigger a policy scale-up within budget, sustained
# idle must scale back to the floor with zero steps lost, a
# fault-injected slow rank must be evicted AND named in the decision
# instrument with its replacement joining warm, and an adversarial
# flapping load must produce no oscillation beyond the hysteresis
# bound (expected decisions +1). Fresh-process retries like 1i/1q —
# loopback rank threads time-slicing a share-throttled box can smear a
# policy window. The passing run's artifact is BENCH_r15.json.
autoscale_bench_gate() {
python bench.py --autoscale-bench | tee /tmp/hvd_autoscale_bench.out | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d.get('error') is None, d.get('error')
assert d['numerics_ok'] is True, d
load, ev, flap = d['load'], d['evict'], d['flap']
assert d['value'] is not None and d['value'] <= 20.0, \
    'scale-up did not fire within the 20 s breach budget: %r' % d['value']
assert ['add', 'slo-breach'] in load['decisions'], load
assert ['remove', 'idle'] in load['decisions'], load
assert load['final_world'] == 2, \
    'idle scale-down did not return to the floor: %r' % load
assert load['scale_down_steps_lost'] == 0, \
    'graceful policy scale-down lost steps: %r' % load
# oscillation bound, load phase: exactly one grow + one shrink (+1)
assert len(load['decisions']) <= 3, load
assert ev['evicted_rank'] == 2, \
    'planted-slow rank 2 not the evicted one: %r' % ev
assert ['evict', 'straggler', 2] in ev['decisions'], ev
assert ev['steps_lost_total'] == 0, 'eviction lost steps: %r' % ev
assert ev['warm_reuses'] > 0, \
    'eviction replacement joined cold (no warm reuse): %r' % ev
assert ev['final_world'] == 3, 'evict+replace changed the world: %r' % ev
assert flap['membership_decisions'] <= 1, \
    'policy oscillated under adversarial flapping: %r' % flap
print('autoscale bench OK: scale-up %.2f s after breach onset, '
      'scale-down lost %d, evicted rank %r (warm reuses %d), flap '
      'decisions %d, decisions %r' % (
          d['value'], load['scale_down_steps_lost'], ev['evicted_rank'],
          ev['warm_reuses'], flap['membership_decisions'],
          load['decisions'] + ev['decisions']))"
}
autoscale_bench_gate || {
  echo "autoscale bench attempt 1 failed; retrying in a fresh process"
  autoscale_bench_gate || {
    echo "autoscale bench attempt 2 failed; final retry in a fresh process"
    autoscale_bench_gate
  }
}
tail -1 /tmp/hvd_autoscale_bench.out > BENCH_r15.json

step "1s/6 composed-scaling gate (DP x SP/EP on one hierarchical mesh; docs/mesh.md)"
# ISSUE 17 acceptance on the loopback 8-device CPU mesh: adding a model
# axis to the composed mesh (dcn=2 x ici_dp=2 x seq|expert=2) must keep
# >=80% per-added-axis efficiency against its control lane (pure DP for
# DP x SP at equal FLOPs; flat data x expert sync for DP x EP at
# identical compute), the two-level gradient sync must match the flat
# sync BIT FOR BIT in the exactness domain (integer-valued f32 +
# power-of-two divisors — any wrong-axis/double-count/padding bug still
# breaks equality; see docs/mesh.md 'Numerics'), the eager two-level
# grouped allreduce must match flat grouped allreduce the same way at
# world=8, and the full DP x SP training trajectory must track pure DP
# at float32 ulp scale. Fresh-process retries like 1i/1k: paired
# round-robin timing on the 2-core box still carries scheduling luck.
composed_bench_gate() {
python scaling_bench.py --composed > /tmp/hvd_composed_bench.out \
  && python -c "
import json
d = json.loads(open('/tmp/hvd_composed_bench.out').readlines()[-1])
assert d['dpsp_sync_bitwise'] is True, \
    'two-level composed sync vs flat not bitwise (DP x SP): %r' % d
assert d['dpep_sync_bitwise'] is True, \
    'two-level composed sync vs flat not bitwise (DP x EP): %r' % d
assert d['grouped_two_level_bitwise'] is True, \
    'eager two-level grouped allreduce vs flat not bitwise: %r' % d
assert d['dpsp_traj_ok'] is True, \
    'DP x SP training trajectory diverged from pure DP: %r' % d
assert d['dpep_traj_ok'] is True, \
    'DP x EP training trajectory diverged from flat-sync control: %r' % d
assert d['value'] is not None and d['value'] >= 0.80, \
    'DP x SP per-added-axis efficiency under 80%%: %r' % d
assert d['dpep_per_axis_efficiency'] >= 0.80, \
    'DP x EP per-added-axis efficiency under 80%%: %r' % d
print('composed bench OK: per-axis efficiency dpsp %.3f, dpep %.3f '
      '(floor 0.80), sync bitwise dpsp=%s dpep=%s grouped=%s, dpsp '
      'trajectory max rel %.2e' % (
          d['value'], d['dpep_per_axis_efficiency'],
          d['dpsp_sync_bitwise'], d['dpep_sync_bitwise'],
          d['grouped_two_level_bitwise'], d['dpsp_traj_max_rel']))"
}
composed_bench_gate || {
  echo "composed bench attempt 1 failed; retrying in a fresh process"
  composed_bench_gate || {
    echo "composed bench attempt 2 failed; final retry in a fresh process"
    composed_bench_gate
  }
}
tail -1 /tmp/hvd_composed_bench.out > BENCH_r17.json

step "1u/6 checkpoint recovery-SLO gate (sharded peer-restore vs rank-0 broadcast; docs/checkpoint.md)"
# ISSUE 18 acceptance at loopback world=4: over the IDENTICAL 4->3->4
# churn at three model sizes, the peer restore must serve FEWER rank-0
# bytes than the HVD_CKPT_PEER_RESTORE=0 broadcast baseline at EVERY
# size and grow sub-linearly against it (rank 0 serves only its own
# shard; the broadcast re-syncs every rank's full tree through rank 0),
# the joiner must actually pull shards (and pull none in the baseline
# lanes), and a ckpt.shard_pull:error probe must take the typed
# degraded path exactly where injected and nowhere else. Gated on the
# deterministic hvd_ckpt_* byte/pull/degraded counters — restore
# wall-clock rides along informationally. Fresh-process retries like
# 1i/1q. The passing run's artifact is BENCH_r18.json.
ckpt_recovery_gate() {
python bench.py --ckpt-recovery-bench | tee /tmp/hvd_ckpt_recovery.out | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d.get('error') is None, d.get('error')
assert d['numerics_ok'] is True, d
lanes = d['lanes']
assert len(lanes) >= 3, 'model-size sweep incomplete: %r' % lanes
for row in lanes:
    peer, bc = row['peer'], row['broadcast']
    assert peer['rank0_bytes'] < bc['rank0_bytes'], \
        'peer restore served no fewer rank-0 bytes at size %d: %r' % (
            row['size'], row)
    assert peer['shards_pulled'] > 0, \
        'peer lane pulled no shards at size %d: %r' % (row['size'], peer)
    assert bc['shards_pulled'] == 0, \
        'broadcast lane pulled shards at size %d: %r' % (row['size'], bc)
    assert peer['degraded'] == 0 and bc['degraded'] == 0, \
        'uninjected lane degraded at size %d: %r' % (row['size'], row)
    assert peer['transitions'] >= 2 and bc['transitions'] >= 2, \
        'churn incomplete at size %d: %r' % (row['size'], row)
# sub-linear growth vs the baseline: as the model grows, the peer
# lane's rank-0 bytes must grow by LESS than the broadcast lane's
pg = lanes[-1]['peer']['rank0_bytes'] - lanes[0]['peer']['rank0_bytes']
bg = (lanes[-1]['broadcast']['rank0_bytes']
      - lanes[0]['broadcast']['rank0_bytes'])
assert pg < bg, \
    'peer rank-0 bytes did not grow sub-linearly vs broadcast: %r vs %r' \
    % (pg, bg)
assert d['value'] is not None and d['value'] < 0.5, \
    'peer/broadcast rank-0 byte ratio not under 0.5: %r' % d['value']
probe = d['degraded_probe']
assert probe['degraded'] > 0, \
    'injected ckpt.shard_pull probe never took the typed degraded ' \
    'path: %r' % probe
assert probe['transitions'] >= 2, 'degraded probe churn incomplete: %r' % probe
print('ckpt recovery OK: rank0-byte ratio %.4f at the largest size '
      '(floor <0.5), peer vs broadcast rank-0 bytes %s, growth %d vs '
      '%d, degraded only when injected (%d)' % (
          d['value'],
          [(r['peer']['rank0_bytes'], r['broadcast']['rank0_bytes'])
           for r in lanes],
          pg, bg, probe['degraded']))"
}
ckpt_recovery_gate || {
  echo "ckpt recovery attempt 1 failed; retrying in a fresh process"
  ckpt_recovery_gate || {
    echo "ckpt recovery attempt 2 failed; final retry in a fresh process"
    ckpt_recovery_gate
  }
}
tail -1 /tmp/hvd_ckpt_recovery.out > BENCH_r18.json

if [[ "${1:-}" == "--fast" ]]; then
  step "fast: examples/mnist.py (hvdrun -np 2) then exit"
  env -u XLA_FLAGS python -m horovod_tpu.runner.launch -np 2 -- \
    python examples/mnist.py --smoke
  echo "--fast: skipping second suite pass + artifact + full example checks"
  exit 0
fi

step "1b/6 test suite, second pass (flake detection)"
python -m pytest tests/ -q -x -o faulthandler_timeout=300

step "1e/6 concurrency invariant checker (threaded stress suites under HVD_DEBUG_INVARIANTS=1)"
# The dev-mode runtime checker (utils/invariants.py): lock-order witness,
# thread-affinity assertions, enqueue-reentrancy guard. The threaded
# stress tests must complete with zero invariant reports — a violation
# raises and fails the run.
env HVD_DEBUG_INVARIANTS=1 timeout -k 10 600 \
  python -m pytest tests/test_pipeline_flush.py tests/test_fusion_cycle.py \
    tests/test_invariants.py -q -o faulthandler_timeout=300

step "1f/6 chaos gate (failure domain under HVD_DEBUG_INVARIANTS=1; docs/robustness.md)"
# Deterministic fault injection + watchdog + retry suite: injected KV
# flaps must be absorbed by the retry ladder, a simulated rank death
# must surface as PeerFailureError on the survivor in seconds with no
# hung waiter, and the elastic driver must blacklist + re-form on spawn
# failures and watchdog peer reports. Runs with the concurrency checker
# on: a coordinated abort that corrupts lock order fails here.
env HVD_DEBUG_INVARIANTS=1 timeout -k 10 600 \
  python -m pytest tests/test_faults.py -q -o faulthandler_timeout=120

step "2/6 driver artifact: single-chip compile check (entry)"
python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn).lower(*args).compile()
print("entry() compile OK")
EOF

step "3/6 driver artifact: multi-chip dryrun (8 virtual devices)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

step "4/6 example smoke runs (single-process 8-dev mesh + np=2 hvdrun, like gen-pipeline.sh:160-290)"
for ex in examples/*.py; do
  echo "--- $ex (1 process, 8 virtual devices)"
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python "$ex" --smoke || fail=1
done
echo "--- examples/mnist.py (hvdrun -np 2)"
env -u XLA_FLAGS python -m horovod_tpu.runner.launch -np 2 -- \
  python examples/mnist.py --smoke || fail=1

step "5/6 eager negotiation microbench (np=2, sanity: both paths work)"
env -u XLA_FLAGS python eager_bench.py --iters 40 --warmup 5 | python -c "
import json, sys
d = json.loads(sys.stdin.readlines()[-1])
assert d['adaptive_cycle']['ops_per_sec'] > 0, d
assert d['fixed_cycle']['ops_per_sec'] > 0, d
print('eager negotiation OK:', d['adaptive_cycle']['ms_per_negotiation'],
      'ms/negotiation adaptive vs', d['fixed_cycle']['ms_per_negotiation'],
      'fixed')" || fail=1

exit $fail
