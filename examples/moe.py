#!/usr/bin/env python
"""Mixture-of-Experts with expert parallelism over the mesh.

Demonstrates the parallelism row SURVEY.md §2.3 marks "primitive only" in
the reference, now first-class here: one expert lives on each chip, and
``horovod_tpu.parallel.moe_alltoall`` routes every chip's tokens to their
top-1 expert (capacity-bounded Switch-style dispatch), exchanges them
over the mesh axis with one alltoall each way, and gate-combines the
outputs. Gradients data-sync with the usual mesh reduction, so MoE
training drops into the standard loop.

Run (single host, virtual 8-chip mesh = 8 experts):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/moe.py
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd


def moe_layer(params, x, axis, capacity):
    """x: (tokens, d) on this chip. Routing, the capacity-bounded
    alltoall dispatch/combine, and the load-balance loss all come from
    the framework (:func:`horovod_tpu.parallel.moe_alltoall`); the
    example supplies only the router projection and this chip's expert
    FFN."""
    logits = x @ params["router"]                    # (tokens, n_expert)

    def expert_fn(t):  # this chip's expert on the tokens it received
        return jax.nn.relu(t @ params["w_in"]) @ params["w_out"]

    return hvd.parallel.moe_alltoall(x, logits, expert_fn, axis,
                                     k=1, capacity=capacity)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--tokens", type=int, default=64,
                        help="tokens per chip")
    parser.add_argument("--d-model", type=int, default=32)
    parser.add_argument("--smoke", action="store_true")
    args = parser.parse_args()

    hvd.init()
    n, axis, mesh = hvd.size(), hvd.axis_name(), hvd.mesh()
    steps = 6 if args.smoke else args.steps
    tokens = 16 if args.smoke else args.tokens
    d = args.d_model
    capacity = max(2 * tokens // n, 4)

    rng = np.random.default_rng(0)
    # synthetic task: each token's target is a fixed rotation of itself —
    # learnable by expert FFNs, with cluster structure for the router
    x_host = rng.standard_normal((n * tokens, d)).astype(np.float32)
    rot = np.linalg.qr(rng.standard_normal((d, d)))[0].astype(np.float32)
    y_host = x_host @ rot

    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    params = {
        "router": jax.random.normal(keys[0], (d, n)) * 0.1,
        "w_in": jax.random.normal(keys[1], (d, 4 * d)) * 0.1,
        "w_out": jax.random.normal(keys[2], (4 * d, d)) * 0.1,
    }
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = optax.adam(3e-3)
    opt_state = tx.init(params)

    def loss_fn(p, xb, yb):
        out, aux = moe_layer(p, xb, axis, capacity)
        return jnp.mean((out - yb) ** 2) + 0.01 * aux

    def step(p, o, xb, yb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        # experts are chip-local but router/weights are replicated: the
        # mesh mean is the data-parallel gradient sync
        g = jax.tree.map(lambda t: lax.pmean(t, axis), g)
        updates, o = tx.update(g, o, p)
        return optax.apply_updates(p, updates), o, lax.pmean(loss, axis)

    sharded = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P()), check_vma=False))

    sh = NamedSharding(mesh, P(axis))
    xb = jax.device_put(x_host, sh)
    yb = jax.device_put(y_host, sh)
    first = last = None
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = sharded(params, opt_state, xb, yb)
        jax.block_until_ready(loss)
        last = float(jnp.ravel(loss)[0])
        if first is None:
            first = last
    dt = time.perf_counter() - t0

    if hvd.rank() == 0:
        print(f"MoE: {n} experts over {n} chips, {tokens} tokens/chip, "
              f"capacity {capacity}: loss {first:.4f} -> {last:.4f} "
              f"in {steps} steps ({dt:.1f}s)")
        assert last < first, "loss did not decrease"
        print("OK")


if __name__ == "__main__":
    main()
