#!/usr/bin/env python
"""Estimator-lite on Spark: ``fit(dataset) -> trained params``.

The Spark-estimator analog (reference ``horovod.spark.keras.KerasEstimator``
with a ``Store``, ``/root/reference/docs/spark.rst`` — role parity; see
``horovod_tpu/spark/estimator.py``): the driver hands data + a model
recipe to ``horovod_tpu.spark.fit``, barrier tasks train with sharded
batches and gradient allreduce, per-epoch checkpoints land at
``store_path``, and a rerun resumes from the latest checkpoint.

Run on a machine with pyspark installed:
    python examples/spark_estimator.py

Without pyspark (CI smoke): prints SKIP and exits 0.
"""

import argparse
import sys
import tempfile


def init_fn(rng, batch):
    """Linear-regression params for the example's (features, labels)."""
    import jax.numpy as jnp
    x, _ = batch
    return {"w": jnp.zeros((x.shape[1], 1), jnp.float32),
            "b": jnp.zeros((1,), jnp.float32)}


def loss_fn(params, batch):
    import jax.numpy as jnp
    x, y = batch
    pred = (x @ params["w"])[:, 0] + params["b"][0]
    return jnp.mean((pred - y) ** 2)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-proc", type=int, default=2)
    parser.add_argument("--epochs", type=int, default=4)
    parser.add_argument("--smoke", action="store_true",
                        help="CI smoke: smallest useful run")
    args = parser.parse_args()
    if args.smoke:
        args.epochs = 2

    try:
        from pyspark.sql import SparkSession
    except ImportError:
        print("SKIP: pyspark not installed")
        return 0

    import numpy as np
    import optax

    import horovod_tpu.spark as hvd_spark

    spark = (SparkSession.builder.master(f"local[{args.num_proc}]")
             .appName("horovod_tpu-spark-estimator")
             .config("spark.ui.enabled", "false").getOrCreate())
    try:
        rng = np.random.default_rng(0)
        x = rng.standard_normal((256, 4)).astype(np.float32)
        y = (x @ np.arange(1.0, 5.0, dtype=np.float32)) + 0.5

        with tempfile.TemporaryDirectory() as store:
            params = hvd_spark.fit(
                (x, y), init_fn, loss_fn, optimizer=optax.sgd(0.05),
                epochs=args.epochs, batch_size=64,
                num_proc=args.num_proc, store_path=store)
        mse = float(np.mean(((x @ np.asarray(params["w"]))[:, 0]
                             + np.asarray(params["b"])[0] - y) ** 2))
        print(f"trained: mse={mse:.4f} w={np.asarray(params['w'])[:, 0]}")
        assert mse < 0.5, mse
        return 0
    finally:
        spark.stop()


if __name__ == "__main__":
    sys.exit(main())
