#!/usr/bin/env python
"""Pipeline-parallel language-model training over a dp x pp mesh.

Demonstrates ``horovod_tpu.parallel.pipeline_apply`` end to end on an
LM-shaped model: a replicated embedding, N residual-MLP blocks split
into one pipeline stage per 'pp' chip (params as plain pytrees — they
shard freely where flax module params cannot), and a replicated output
head. Gradients: dp pmean for data parallelism; the pipeline's own
custom-VJP conventions make stage grads exactly-once and embedding/head
grads replica-consistent over pp with no extra collectives.

Run (CPU mesh): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/pipeline_train.py --smoke
"""

import argparse
import sys


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--pp", type=int, default=2,
                        help="pipeline stages (chips along 'pp')")
    parser.add_argument("--smoke", action="store_true")
    args = parser.parse_args()
    if args.smoke:
        args.steps = 8

    import os

    import jax
    if args.smoke or os.environ.get("JAX_PLATFORMS") == "cpu":
        # CI smoke runs on the virtual CPU mesh; on real hardware let
        # jax pick the accelerator
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.parallel import (
        pipeline_apply,
        stack_stage_params,
        unstack_stage,
    )

    hvd.init()
    n = hvd.size()
    if args.pp < 1 or n % args.pp:
        raise SystemExit(
            f"--pp {args.pp} must be a positive divisor of the "
            f"{n}-device world")
    pp = args.pp
    dp = n // pp
    mesh = Mesh(np.array(jax.devices()[:n]).reshape(dp, pp), ("dp", "pp"))

    vocab, d_model, seq, layers_per_stage = 64, 32, 16, 2
    rng = np.random.default_rng(0)

    def init_block():
        return {"wi": jnp.asarray(
                    rng.standard_normal((d_model, 4 * d_model)) * 0.05,
                    jnp.float32),
                "wo": jnp.asarray(
                    rng.standard_normal((4 * d_model, d_model)) * 0.05,
                    jnp.float32)}

    params = {
        "embed": jnp.asarray(rng.standard_normal((vocab, d_model)) * 0.1,
                             jnp.float32),
        "stages": stack_stage_params(
            [{"blocks": [init_block() for _ in range(layers_per_stage)]}
             for _ in range(pp)]),
        "head": jnp.asarray(rng.standard_normal((d_model, vocab)) * 0.1,
                            jnp.float32),
    }

    def stage_fn(stage_params, h):
        for blk in stage_params["blocks"]:
            h = h + jnp.tanh(h @ blk["wi"]) @ blk["wo"]  # residual MLP
        return h

    # toy task: predict the next token of a fixed random sequence
    tokens = rng.integers(0, vocab, (8 * dp, seq + 1))
    x_host, y_host = tokens[:, :-1], tokens[:, 1:]

    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            h = p["embed"][x]  # replicated embed, dp-sharded batch
            h = pipeline_apply(stage_fn, unstack_stage(p["stages"]), h,
                               "pp", n_microbatches=4)
            logits = h @ p["head"]
            one_hot = jax.nn.one_hot(y, vocab)
            return -jnp.mean(jnp.sum(
                one_hot * jax.nn.log_softmax(logits), -1))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                jax.lax.pmean(loss, "dp"))

    # Stage tensors shard over 'pp' (their leading dim is the stage);
    # embed/head and adam's scalar count replicate. Per-leaf specs make
    # both the shard_map signature and the device_put placements.
    def spec_of(leaf):
        if jnp.ndim(leaf) >= 1 and leaf.shape[:1] == (pp,):
            return P("pp")
        return P()

    def put_with_specs(tree, specs):
        return jax.tree.map(
            lambda l, s: jax.device_put(l, NamedSharding(mesh, s)),
            tree, specs)

    param_specs = {"embed": P(),
                   "stages": jax.tree.map(lambda _: P("pp"),
                                          params["stages"]),
                   "head": P()}
    opt_specs = jax.tree.map(spec_of, opt_state)
    in_specs = (param_specs, opt_specs, P("dp"), P("dp"))
    out_specs = (in_specs[0], in_specs[1], P())
    step = jax.jit(jax.shard_map(train_step, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))

    params = put_with_specs(params, param_specs)
    opt_state = put_with_specs(opt_state, opt_specs)
    xs = jax.device_put(x_host, NamedSharding(mesh, P("dp")))
    ys = jax.device_put(y_host, NamedSharding(mesh, P("dp")))

    losses = []
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, xs, ys)
        losses.append(float(jax.block_until_ready(loss)))
    print(f"pp={pp} dp={dp}: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"in {len(losses)} steps")
    assert losses[-1] < losses[0], losses
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
