#!/usr/bin/env python
"""Training on Spark executors via ``horovod_tpu.spark.run``.

The Spark analog of ``examples/mnist.py`` (reference ``horovod.spark.run``
usage, ``/root/reference/docs/spark.rst``): one barrier-mode task per
rank, results returned rank-ordered.

Run on a machine with pyspark installed:
    python examples/spark_train.py

Without pyspark (CI smoke): prints SKIP and exits 0.
"""

import argparse
import sys


def train_fn(steps: int = 10):
    """One rank: the usual five-line pattern. Defined HERE (the __main__
    module) and fully self-contained, so pyspark's cloudpickle serializes
    it by value — importing it from a sibling example module would make
    executors try `import ray_train`, which is only on the driver's
    sys.path."""
    import os
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.default_rng(hvd.rank())
    w_true = jnp.asarray([[2.0], [-3.0]])
    params = hvd.broadcast_parameters({"w": jnp.zeros((2, 1))}, root_rank=0)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    opt = tx.init(params)
    mesh, axis = hvd.mesh(), hvd.axis_name()

    def step(p, o, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    sharded = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P()), check_vma=False))
    sh = NamedSharding(mesh, P(axis))
    n = hvd.size()
    x = jax.device_put(rng.standard_normal((4 * n, 2)).astype("float32"), sh)
    y = jax.device_put(np.asarray(x) @ np.asarray(w_true), sh)
    loss = None
    for _ in range(steps):
        params, opt, loss = sharded(params, opt, x, y)
        jax.block_until_ready(loss)
    return float(loss)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-proc", type=int, default=2)
    parser.add_argument("--smoke", action="store_true",
                        help="fewer training steps")
    args = parser.parse_args()

    try:
        from pyspark.sql import SparkSession
    except ImportError:
        print("SKIP: pyspark not installed (install pyspark to run this "
              "example)")
        return 0

    import horovod_tpu.spark as hvd_spark

    spark = (SparkSession.builder.master(f"local[{args.num_proc}]")
             .appName("horovod_tpu-spark-example").getOrCreate())
    try:
        results = hvd_spark.run(train_fn, args=(3 if args.smoke else 10,),
                                num_proc=args.num_proc)
    finally:
        spark.stop()
    print(f"final losses per rank: {results}")
    assert all(l < 1.0 for l in results)
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
