#!/usr/bin/env python
"""Training on Spark executors via ``horovod_tpu.spark.run``.

The Spark analog of ``examples/mnist.py`` (reference ``horovod.spark.run``
usage, ``/root/reference/docs/spark.rst``): one barrier-mode task per
rank, results returned rank-ordered.

Run on a machine with pyspark installed:
    python examples/spark_train.py

Without pyspark (CI smoke): prints SKIP and exits 0.
"""

import argparse
import sys

from ray_train import train_fn  # the same per-rank fn works everywhere


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-proc", type=int, default=2)
    parser.add_argument("--smoke", action="store_true")
    args = parser.parse_args()

    try:
        from pyspark.sql import SparkSession
    except ImportError:
        print("SKIP: pyspark not installed (install pyspark to run this "
              "example)")
        return 0

    import horovod_tpu.spark as hvd_spark

    spark = (SparkSession.builder.master(f"local[{args.num_proc}]")
             .appName("horovod_tpu-spark-example").getOrCreate())
    try:
        results = hvd_spark.run(train_fn, num_proc=args.num_proc)
    finally:
        spark.stop()
    print(f"final losses per rank: {results}")
    assert all(l < 1.0 for l in results)
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
