#!/usr/bin/env python
"""MNIST-style training example — the TPU-native mirror of the reference's
``examples/pytorch/pytorch_mnist.py`` (DistributedOptimizer, size-scaled LR
with warmup, parameter broadcast at step 0, metric averaging at epoch end).

The dataset is synthetic (this environment has no egress): 28x28 "digits"
are class-colored Gaussian blobs — enough structure for the loss to fall
and accuracy to rise, which is what the example demonstrates.

Run (single host, virtual 8-chip mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/mnist.py

Run (multi-process, hvdrun):
    python -m horovod_tpu.runner.launch -np 2 -- python examples/mnist.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import flax.linen as nn

import horovod_tpu as hvd


class ConvNet(nn.Module):
    """The reference example's small conv net (pytorch_mnist.py Net)."""

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(16, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(32, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def synthetic_mnist(n, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    images = rng.standard_normal((n, 28, 28, 1)).astype(np.float32) * 0.3
    xx, yy = np.meshgrid(np.arange(28), np.arange(28))
    for digit in range(10):
        cx, cy = 4 + 2 * (digit % 5), 6 + 7 * (digit // 5)
        blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / 18)).astype(
            np.float32)
        images[labels == digit, :, :, 0] += blob
    return images, labels


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="global batch size")
    parser.add_argument("--base-lr", type=float, default=0.01,
                        help="per-worker learning rate (scaled by size)")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny run for CI")
    args = parser.parse_args()
    if args.smoke:
        args.epochs = 1

    hvd.init()
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    batch = max(args.batch_size // n, 1) * n

    images, labels = synthetic_mnist(512 if args.smoke else 8192)
    loader = hvd.data.ShardedArrayLoader(images, labels, batch_size=batch)

    model = ConvNet()
    params = model.init(jax.random.PRNGKey(42 + hvd.rank()),
                        jnp.zeros((1, 28, 28, 1)))["params"]

    steps_per_epoch = len(loader)
    # Reference recipe: lr scaled by size, warmed up over the first epochs.
    schedule = hvd.callbacks.warmup_schedule(
        args.base_lr * n, steps_per_epoch=steps_per_epoch, warmup_epochs=1)
    tx = hvd.DistributedOptimizer(optax.sgd(schedule, momentum=0.9))
    opt_state = tx.init(params)

    # BroadcastGlobalVariablesCallback analog: rank 0's weights everywhere.
    params = hvd.broadcast_parameters(params, root_rank=0)

    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            one_hot = jax.nn.one_hot(y, 10)
            loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
            acc = jnp.mean(jnp.argmax(logits, -1) == y)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh, in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()), check_vma=False))

    first_loss = None
    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        losses, accs = [], []
        for x, y in loader:
            params, opt_state, loss, acc = step(params, opt_state, x, y)
            losses.append(float(jax.block_until_ready(loss)))
            accs.append(float(acc))
        # MetricAverageCallback analog: epoch metrics averaged across ranks
        logs = hvd.average_metrics(
            {"loss": np.mean(losses), "accuracy": np.mean(accs)})
        if first_loss is None:
            first_loss = logs["loss"]
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={logs['loss']:.4f} "
                  f"accuracy={logs['accuracy']:.3f}")
    assert logs["loss"] < first_loss * 1.001 or logs["accuracy"] > 0.2, \
        "training made no progress"
    if hvd.rank() == 0:
        print("OK")


if __name__ == "__main__":
    main()
