#!/usr/bin/env python
"""Synthetic throughput benchmark — the TPU-native mirror of the
reference's ``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``
(ResNet-50 on synthetic ImageNet batches, DistributedGradientTape,
``--fp16-allreduce``). The repo-root ``bench.py`` is the driver-facing
variant with MFU accounting; this example shows the user-facing recipe.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/synthetic_benchmark.py --model ResNet18 \
        --image-size 32 --batch-size 16 --num-iters 3
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import models as hvd_models


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="ResNet50",
                        choices=["ResNet18", "ResNet34", "ResNet50",
                                 "ResNet101", "ResNet152"])
    parser.add_argument("--batch-size", type=int, default=32,
                        help="per-chip batch size (reference default)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--num-warmup", type=int, default=2)
    parser.add_argument("--fp16-allreduce", action="store_true",
                        help="compress gradients on the wire (reference "
                             "--fp16-allreduce)")
    parser.add_argument("--smoke", action="store_true")
    args = parser.parse_args()
    if args.smoke:
        args.model, args.image_size = "ResNet18", 32
        args.batch_size, args.num_iters, args.num_warmup = 4, 2, 1

    hvd.init()
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()

    model_cls = getattr(hvd_models, args.model)
    model = model_cls(num_classes=1000, dtype=jnp.bfloat16, axis_name=None)
    s = args.image_size
    images = np.random.default_rng(0).standard_normal(
        (n * args.batch_size, s, s, 3), dtype=np.float32)
    labels = np.random.default_rng(1).integers(
        0, 1000, size=(n * args.batch_size,))

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, s, s, 3)), train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  compression=compression)
    opt_state = tx.init(params)

    def train_step(params, batch_stats, opt_state, x, y):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(y, 1000)
            loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()), check_vma=False))

    data_sharding = NamedSharding(mesh, P(axis))
    x = jax.device_put(images, data_sharding)
    y = jax.device_put(labels, data_sharding)

    for _ in range(args.num_warmup):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y)
    jax.block_until_ready((params, loss))
    elapsed = time.perf_counter() - t0

    img_sec = args.num_iters * args.batch_size * n / elapsed
    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/chip, "
              f"{n} chips")
        print(f"Total img/sec on {n} chip(s): {img_sec:.1f} "
              f"({img_sec / n:.1f} per chip)")
        print("OK")


if __name__ == "__main__":
    main()
