#!/usr/bin/env python
"""Training on a Ray cluster — static and elastic executors.

The Ray analog of ``examples/mnist.py`` (reference ``horovod.ray`` usage,
``/root/reference/docs/ray.rst``): actors replace ssh placement, the
worker fn is ordinary framework code starting with ``hvd.init()``.

Run on a machine with Ray installed:
    python examples/ray_train.py                # static, 2 workers
    python examples/ray_train.py --elastic      # elastic, min 2 workers

Without Ray (CI smoke): prints SKIP and exits 0.
"""

import argparse
import sys


def train_fn(steps: int = 10):
    """One rank: the usual five-line pattern."""
    import os
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.default_rng(hvd.rank())
    w_true = jnp.asarray([[2.0], [-3.0]])
    params = {"w": jnp.zeros((2, 1))}
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    opt = tx.init(params)

    mesh, axis = hvd.mesh(), hvd.axis_name()

    def step(p, o, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    sharded = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P()), check_vma=False))
    from jax.sharding import NamedSharding
    sh = NamedSharding(mesh, P(axis))
    n = hvd.size()
    x = jax.device_put(rng.standard_normal((4 * n, 2)).astype("float32"), sh)
    y = jax.device_put(np.asarray(x) @ np.asarray(w_true), sh)
    loss = None
    for _ in range(steps):
        params, opt, loss = sharded(params, opt, x, y)
        jax.block_until_ready(loss)
    return float(loss)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--elastic", action="store_true")
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--smoke", action="store_true",
                        help="fewer training steps")
    args = parser.parse_args()
    steps = 3 if args.smoke else 10

    try:
        import ray  # noqa: F401
    except ImportError:
        print("SKIP: ray not installed (install Ray to run this example)")
        return 0

    if args.elastic:
        from horovod_tpu.ray import ElasticRayExecutor
        ex = ElasticRayExecutor(min_workers=args.workers)
        ex.start()
        try:
            # elastic worker fns wrap their loop in hvd.elastic.run; this
            # demo uses the static-shaped fn for brevity
            results = ex.run(train_fn, args=(steps,))
        finally:
            ex.shutdown()
    else:
        from horovod_tpu.ray import RayExecutor
        ex = RayExecutor(num_workers=args.workers)
        ex.start()
        try:
            results = ex.run(train_fn, args=(steps,))
        finally:
            ex.shutdown()
    print(f"final losses per rank: {results}")
    assert all(l < 1.0 for l in results)
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
