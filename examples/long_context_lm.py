#!/usr/bin/env python
"""Long-context LM training with sequence parallelism.

Demonstrates the framework's context-parallel schedules (no reference
analog — the reference is data-parallel only, SURVEY.md §5.7): the
sequence dimension is sharded over the mesh, attention runs as **ring
attention** (K/V blocks rotating over `ppermute` with the online-softmax
recurrence and an O(block)-memory backward) or **Ulysses** (all-to-all
seq<->head resharding), and gradients data-sync through the usual mesh
reduction — sequence parallelism composes with the Horovod-style training
loop unchanged.

Run (single host, virtual 8-chip mesh; each chip holds seq/8 tokens):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context_lm.py

Flags: --attn ring|ring_zigzag|ulysses, --seq-len, --smoke (tiny shapes, few steps).
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import TransformerConfig, TransformerLM


def synthetic_tokens(n_seqs, seq_len, vocab, seed=0):
    """Deterministic structure (arithmetic progressions mod vocab) so the
    LM has something learnable at every context position."""
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab, size=(n_seqs, 1))
    step = rng.integers(1, 7, size=(n_seqs, 1))
    pos = np.arange(seq_len)[None, :]
    return ((start + step * pos) % vocab).astype(np.int32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--attn", choices=("ring", "ring_zigzag", "ulysses"),
                        default="ring")
    parser.add_argument("--seq-len", type=int, default=None,
                        help="total context length (default 64 tokens/chip)")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--smoke", action="store_true")
    args = parser.parse_args()

    hvd.init()
    n, axis, mesh = hvd.size(), hvd.axis_name(), hvd.mesh()
    seq = args.seq_len or (16 if args.smoke else 64) * n
    if seq % n:
        raise SystemExit(f"--seq-len must divide by {n} chips")
    steps = 5 if args.smoke else args.steps

    cfg = TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=8, d_model=64, d_ff=128,
        max_seq_len=seq, dtype=jnp.float32,
        attn_mode=args.attn, seq_axis=axis)
    model = TransformerLM(cfg)
    tokens = synthetic_tokens(args.batch, seq, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens))["params"]
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    def loss_fn(p, t):
        logits = model.apply({"params": p}, t)
        tgt = jnp.roll(t, -1, axis=1)
        return -jnp.mean(jnp.take_along_axis(
            jax.nn.log_softmax(logits), tgt[..., None], -1)[:, :-1])

    def step(p, o, t):
        loss, g = jax.value_and_grad(loss_fn)(p, t)
        # every chip computed grads from its sequence block: mean over
        # the mesh is the full-sequence gradient — and the same mean turns
        # the chip-local block loss into the full-sequence loss
        g = jax.tree.map(lambda x: jax.lax.pmean(x, axis), g)
        updates, o = tx.update(g, o, p)
        return optax.apply_updates(p, updates), o, jax.lax.pmean(loss, axis)

    sharded = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(None, axis)),
        out_specs=(P(), P(), P()), check_vma=False))

    t = jax.device_put(tokens, NamedSharding(mesh, P(None, axis)))
    first = last = None
    t0 = time.perf_counter()
    for i in range(steps):
        params, opt_state, loss = sharded(params, opt_state, t)
        jax.block_until_ready(loss)
        last = float(loss)
        if first is None:
            first = last
    dt = time.perf_counter() - t0

    if hvd.rank() == 0:
        print(f"{args.attn} attention over {n} chips, seq={seq} "
              f"({seq // n} tokens/chip): loss {first:.3f} -> {last:.3f} "
              f"in {steps} steps ({dt:.1f}s)")
        assert last < first, "loss did not decrease"
        print("OK")


if __name__ == "__main__":
    main()
