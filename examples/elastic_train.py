#!/usr/bin/env python
"""Elastic training example — the TPU-native mirror of the reference's
``examples/elastic/pytorch/pytorch_mnist_elastic.py``: state
commit/restore with ``hvd.elastic.run``, an :class:`ElasticSampler`
re-partitioning the remaining epoch after membership changes.

Single-process smoke (no driver — the recovery loop still runs):
    python examples/elastic_train.py --smoke

Real elastic launch:
    python -m horovod_tpu.runner.launch -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh -- \
        python examples/elastic_train.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-per-rank", type=int, default=8)
    parser.add_argument("--smoke", action="store_true")
    args = parser.parse_args()
    if args.smoke:
        args.epochs = 2

    hvd.init()

    rng = np.random.default_rng(0)
    data_x = rng.standard_normal((256, 16)).astype(np.float32)
    true_w = rng.standard_normal((16, 1)).astype(np.float32)
    data_y = data_x @ true_w

    params = {"w": jnp.zeros((16, 1), jnp.float32)}
    tx = hvd.DistributedOptimizer(optax.sgd(0.05))
    sampler = hvd.elastic.ElasticSampler(len(data_x), seed=1)

    state = hvd.elastic.JaxState(
        params=params, opt_state=tx.init(params),
        sampler=sampler.state_dict(), epoch=0, losses=[])
    state.register_reset_callbacks(
        [lambda: sampler.load_state_dict(state.sampler)])

    def train_step_fn(mesh, axis):
        def train_step(params, opt_state, x, y):
            def loss_fn(p):
                return jnp.mean((x @ p["w"] - y) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state2 = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state2, loss
        return jax.jit(jax.shard_map(
            train_step, mesh=mesh, in_specs=(P(), P(), P(axis), P(axis)),
            out_specs=(P(), P(), P()), check_vma=False))

    @hvd.elastic.run
    def train(state):
        # (re)compiled per membership: the mesh changes when the world does
        mesh, axis = hvd.mesh(), hvd.axis_name()
        step = train_step_fn(mesh, axis)
        sharding = NamedSharding(mesh, P(axis))
        nproc = hvd.process_count()
        # the sharded global batch (per_rank * nproc) must divide by the
        # chip count — round per_rank up to the smallest multiple that
        # satisfies it (heterogeneous hosts included: the unit is
        # size/gcd(size, nproc), not size//nproc)
        import math
        unit = hvd.size() // math.gcd(hvd.size(), nproc)
        per_rank = -(-args.batch_per_rank // unit) * unit
        batch = per_rank * nproc
        for state.epoch in range(state.epoch, args.epochs):
            idx_all = sampler.local_indices()
            for start in range(0, len(idx_all) - per_rank + 1, per_rank):
                # the sampler partitions per data-feeding process; the
                # global batch is the concatenation of every process's
                # slice. Each process only materializes its own region of
                # the global array, so tiling its slice nproc times places
                # the right rows at its offset — the batch covers `batch`
                # DISTINCT samples globally, sharded over all chips.
                local = idx_all[start:start + per_rank]
                gx = np.concatenate(
                    [data_x[local]] * nproc) if nproc > 1 else data_x[local]
                gy = np.concatenate(
                    [data_y[local]] * nproc) if nproc > 1 else data_y[local]
                x = jax.device_put(gx[:batch], sharding)
                y = jax.device_put(gy[:batch], sharding)
                state.params, state.opt_state, loss = step(
                    state.params, state.opt_state, x, y)
                sampler.record_batch(per_rank)
                state.sampler = sampler.state_dict()
                state.losses = state.losses + [
                    float(jax.block_until_ready(loss))]
                state.commit()
            sampler.set_epoch(state.epoch + 1)
            state.sampler = sampler.state_dict()
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss={state.losses[-1]:.5f}")
        return state.losses

    losses = train(state)
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    if hvd.rank() == 0:
        print("OK")


if __name__ == "__main__":
    main()
