#!/usr/bin/env python
"""Eager multi-process negotiation overhead microbenchmark (np=2).

Measures what one eager collective costs when every op must be negotiated
through the dynamic engine over the HTTP KV (two real worker processes,
CPU backend — the negotiation is host-side, so the accelerator is
irrelevant). The reference's equivalent cost is one in-process
``RunLoopOnce`` cycle (1 ms default ``CycleTimeMs``,
``/root/reference/horovod/common/operations.cc:499-506``); over a KV
transport each cycle is an HTTP gather round, so the floor is the KV RTT.

Prints ONE JSON line:
  {"metric": "eager_negotiated_allreduce_ops_per_sec", "value": ...,
   "adaptive_cycle": {...}, "fixed_cycle": {...}}

comparing the event-driven adaptive tick (default; fresh enqueues wake
the cycle loop, in-flight work lowers the pace floor to
``HVD_PENDING_CYCLE_TIME``) against the fixed 20 ms cadence
(``HVD_ADAPTIVE_CYCLE=0``). Where the eager path stops being appropriate
is documented in docs/benchmarks.md — these numbers are the basis.
"""

import json
import os
import sys


def _worker(iters: int, warmup: int):
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    import time

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    x = jnp.ones((1024,), jnp.float32)
    for i in range(warmup):
        jax.block_until_ready(hvd.allreduce(x, name=f"warmup_{i}"))
    t0 = time.perf_counter()
    for i in range(iters):
        jax.block_until_ready(hvd.allreduce(x, name=f"bench_{i}"))
    dt = time.perf_counter() - t0

    # negotiation alone (no collective execution): the engine-service cost
    # an eager op pays on top of the XLA program
    from horovod_tpu import engine_service
    from horovod_tpu.dynamic import REQ_ALLREDUCE
    svc = engine_service.get_service()
    t0 = time.perf_counter()
    for i in range(iters):
        svc.negotiate(f"neg_{i}", REQ_ALLREDUCE, shape=(1024,))
    dneg = time.perf_counter() - t0
    return {"ops_per_sec": iters / dt, "ms_per_op": dt / iters * 1e3,
            "negotiations_per_sec": iters / dneg,
            "ms_per_negotiation": dneg / iters * 1e3}


def _measure(adaptive: bool, iters: int, warmup: int) -> dict:
    from horovod_tpu.runner import run as hvd_run

    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "HVD_ADAPTIVE_CYCLE": "1" if adaptive else "0",
    }
    results = hvd_run(_worker, args=(iters, warmup), np=2, env=env,
                      start_timeout=300.0)
    # both ranks time the same negotiated sequence; report rank 0
    return {k: round(v, 3) for k, v in results[0].items()}


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--iters", type=int, default=200)
    parser.add_argument("--warmup", type=int, default=20)
    args = parser.parse_args()

    adaptive = _measure(True, args.iters, args.warmup)
    fixed = _measure(False, args.iters, args.warmup)
    print(json.dumps({
        "metric": "eager_negotiated_allreduce_ops_per_sec",
        "value": adaptive["ops_per_sec"],
        "unit": "ops/sec",
        "np": 2,
        "payload_bytes": 4096,
        "adaptive_cycle": adaptive,
        "fixed_cycle": fixed,
        "speedup_vs_fixed": round(
            adaptive["ops_per_sec"] / fixed["ops_per_sec"], 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
