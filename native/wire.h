/* Little-endian binary serialization helpers for the engine wire format.
 *
 * The reference serializes Request/Response lists with FlatBuffers
 * (wire/message.fbs); this rebuild uses a hand-rolled fixed little-endian
 * layout instead — the payloads are tiny (tensor names + shapes), both ends
 * are this library, and zero third-party dependencies keeps the build to a
 * single g++ invocation.
 */

#ifndef HVD_WIRE_H
#define HVD_WIRE_H

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace hvd {

class Writer {
 public:
  std::vector<uint8_t> buf;

  void u8(uint8_t v) { buf.push_back(v); }
  void u32(uint32_t v) {
    for (int i = 0; i < 4; ++i) buf.push_back((v >> (8 * i)) & 0xff);
  }
  void i32(int32_t v) { u32(static_cast<uint32_t>(v)); }
  void u64(uint64_t v) {
    for (int i = 0; i < 8; ++i) buf.push_back((v >> (8 * i)) & 0xff);
  }
  void i64(int64_t v) { u64(static_cast<uint64_t>(v)); }
  void f64(double v) {
    uint64_t bits;
    std::memcpy(&bits, &v, 8);
    u64(bits);
  }
  void str(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    buf.insert(buf.end(), s.begin(), s.end());
  }
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : data_(data), len_(len), pos_(0) {}

  uint8_t u8() {
    need(1);
    return data_[pos_++];
  }
  uint32_t u32() {
    need(4);
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= static_cast<uint32_t>(data_[pos_++]) << (8 * i);
    return v;
  }
  int32_t i32() { return static_cast<int32_t>(u32()); }
  uint64_t u64() {
    need(8);
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v |= static_cast<uint64_t>(data_[pos_++]) << (8 * i);
    return v;
  }
  int64_t i64() { return static_cast<int64_t>(u64()); }
  double f64() {
    uint64_t bits = u64();
    double v;
    std::memcpy(&v, &bits, 8);
    return v;
  }
  std::string str() {
    uint32_t n = u32();
    need(n);
    std::string s(reinterpret_cast<const char*>(data_ + pos_), n);
    pos_ += n;
    return s;
  }
  bool done() const { return pos_ >= len_; }

 private:
  void need(size_t n) {
    if (pos_ + n > len_) throw std::runtime_error("wire: truncated buffer");
  }
  const uint8_t* data_;
  size_t len_;
  size_t pos_;
};

}  // namespace hvd

#endif  // HVD_WIRE_H
