/* hvd_core: C API of the horovod_tpu native dynamic engine.
 *
 * TPU-native rebuild of the reference's C++ core runtime
 * (/root/reference/horovod/common/: operations.cc, controller.cc,
 * tensor_queue.cc, response_cache.cc, fusion_buffer_manager.cc,
 * group_table.cc, stall_inspector.cc, timeline.cc). The split of labor is
 * inverted for TPU (SURVEY.md §7): XLA executes the collectives, so this
 * engine owns everything *around* execution — request queueing, readiness
 * negotiation bookkeeping, response caching, fusion planning, stall
 * detection, and timeline tracing — and hands fused execution plans back to
 * the Python/jax layer.
 *
 * The negotiation is symmetric rather than master-worker: every rank
 * ingests the identical, rank-ordered set of serialized request lists and
 * deterministically computes the same response plan (the coordinator
 * protocol of controller.h:72-108 degenerates to this when the transport is
 * an allgather, which is the natural collective on a TPU mesh).
 *
 * All buffers returned through out-parameters are owned by the engine's
 * last call on that slot and remain valid until the next call on the same
 * engine from the same thread; copy out before re-entering.
 */

#ifndef HVD_CORE_H
#define HVD_CORE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* hvd_engine_t;

/* Request/response types, mirroring message.h:52-54,155-157 */
enum {
  HVD_REQ_ALLREDUCE = 0,
  HVD_REQ_ALLGATHER = 1,
  HVD_REQ_BROADCAST = 2,
  HVD_REQ_JOIN = 3,
  HVD_REQ_ADASUM = 4,
  HVD_REQ_ALLTOALL = 5,
  HVD_REQ_BARRIER = 6,
  HVD_REQ_REDUCESCATTER = 7
};

enum {
  HVD_RESP_ALLREDUCE = 0,
  HVD_RESP_ALLGATHER = 1,
  HVD_RESP_BROADCAST = 2,
  HVD_RESP_JOIN = 3,
  HVD_RESP_ADASUM = 4,
  HVD_RESP_ALLTOALL = 5,
  HVD_RESP_BARRIER = 6,
  HVD_RESP_REDUCESCATTER = 7,
  HVD_RESP_ERROR = 8
};

/* engine lifecycle ------------------------------------------------------- */

hvd_engine_t hvd_engine_create(int32_t world_size, int32_t rank,
                               int64_t fusion_threshold_bytes,
                               int32_t cache_capacity,
                               double stall_warn_seconds,
                               double stall_shutdown_seconds);
void hvd_engine_destroy(hvd_engine_t engine);

/* worker side ------------------------------------------------------------ */

/* Enqueue a named tensor request (EnqueueTensorAllreduce et al.,
 * operations.cc:1357-1795). dtype is an opaque small int chosen by the
 * caller (only equality matters for mismatch checks / fusion classes);
 * element_size is bytes per element for fusion accounting. root_rank is
 * used by BROADCAST, group_id groups tensors for joint fusion (-1 = none).
 * splits/nsplits carry ALLTOALL uneven-splits metadata (how many dim-0
 * rows this rank sends each rank; NULL/0 = even splits); the negotiated
 * recv-splits come back on the ALLTOALL response.
 * Returns 0 (queued), 1 (re-attached to this rank's still-in-flight
 * negotiation after an abandon — no new wire request is emitted), -1 on
 * duplicate name still pending (common.h:229-232), -2 when a
 * post-abandon retry's metadata differs from the in-flight negotiation,
 * or -3 on invalid splits (wrong length, negative, sum > dim0). */
/* reduce_op/prescale/postscale: wire-lowered reduce parameters for the
 * ALLREDUCE family — validated for cross-rank agreement and echoed on the
 * response so a JOINed rank can reconstruct the identical program. */
int32_t hvd_engine_enqueue(hvd_engine_t engine, const char* name,
                           int32_t request_type, int32_t dtype,
                           int32_t element_size, const int64_t* shape,
                           int32_t ndim, int32_t root_rank, int32_t group_id,
                           const int32_t* splits, int32_t nsplits,
                           int32_t reduce_op, double prescale,
                           double postscale, int32_t splits_crc);

/* Serialize and clear this rank's pending requests (the per-cycle
 * PopMessagesFromQueue, controller.cc:92). */
int32_t hvd_engine_pop_requests(hvd_engine_t engine, const uint8_t** out,
                                size_t* out_len);

/* negotiation (symmetric) ------------------------------------------------ */

/* Ingest one rank's serialized request list for this cycle. Must be called
 * for every rank (including self) in rank order on every member. */
int32_t hvd_engine_ingest(hvd_engine_t engine, int32_t rank,
                          const uint8_t* data, size_t len);

/* Compute the fused response plan for every tensor now ready on all ranks
 * (ComputeResponseList + FuseResponses, controller.cc:73-430). The result
 * is a serialized ResponseList; identical on every rank by construction.
 * Also advances stall bookkeeping. */
int32_t hvd_engine_compute_responses(hvd_engine_t engine, const uint8_t** out,
                                     size_t* out_len);

/* response cache --------------------------------------------------------- */

/* Bit vector (little-endian bytes) of cache entries this rank could serve
 * from cache for its *pending* requests; AND-reduce across ranks and pass
 * to hvd_engine_commit_cache_bits (CoordinateCacheAndState,
 * response_cache.h:107-169). */
int32_t hvd_engine_cache_bits(hvd_engine_t engine, const uint8_t** out,
                              size_t* out_len);

/* Commit the globally ANDed bit vector: pending requests whose cache bit
 * survived are moved into the response plan without full negotiation. */
int32_t hvd_engine_commit_cache_bits(hvd_engine_t engine, const uint8_t* bits,
                                     size_t len);

/* Abandon a locally-submitted request (e.g. after a negotiation timeout)
 * so its name can be enqueued again. Returns 0, or -1 if the name is not
 * outstanding. */
int32_t hvd_engine_abandon(hvd_engine_t engine, const char* name);

/* stall inspector -------------------------------------------------------- */

/* Returns a serialized report of tensors submitted by some-but-not-all
 * ranks for longer than stall_warn_seconds (stall_inspector.h:75-86):
 * u32 count, then per entry: str name, u32 n_ready, u32 ready_ranks[],
 * f64 waiting_seconds. Returns 1 if the shutdown threshold was crossed. */
int32_t hvd_engine_stall_report(hvd_engine_t engine, const uint8_t** out,
                                size_t* out_len);

/* timeline --------------------------------------------------------------- */

int32_t hvd_timeline_start(hvd_engine_t engine, const char* path);
void hvd_timeline_stop(hvd_engine_t engine);
/* phase: 0 = begin, 1 = end, 2 = instant */
void hvd_timeline_record(hvd_engine_t engine, const char* tensor,
                         const char* activity, int32_t phase,
                         int64_t timestamp_us);

/* introspection ---------------------------------------------------------- */

int32_t hvd_engine_pending_count(hvd_engine_t engine);
int32_t hvd_engine_cache_size(hvd_engine_t engine);
/* 1 when `name` is held by the response cache (stream-driven invalidation
 * keeps the answer identical on every rank per cycle). */
int32_t hvd_engine_cache_has(hvd_engine_t engine, const char* name);
/* 1 while any rank's JOIN is in flight (ingested, not yet completed). */
int32_t hvd_engine_join_pending(hvd_engine_t engine);
const char* hvd_core_version(void);

#ifdef __cplusplus
}
#endif

#endif /* HVD_CORE_H */
