/* Request/Response message types for the dynamic engine.
 *
 * TPU-native rebuild of the reference's message layer
 * (/root/reference/horovod/common/message.h:52-157 — Request{ALLREDUCE,
 * ALLGATHER, BROADCAST, JOIN, ADASUM, ALLTOALL, BARRIER}, Response{...,
 * ERROR}, RequestList/ResponseList) with a hand-rolled wire format
 * (see wire.h) instead of FlatBuffers.
 */

#ifndef HVD_MESSAGE_H
#define HVD_MESSAGE_H

#include <cstdint>
#include <string>
#include <vector>

#include "wire.h"

namespace hvd {

enum class RequestType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ADASUM = 4,
  ALLTOALL = 5,
  BARRIER = 6,
  REDUCESCATTER = 7,
};

enum class ResponseType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ADASUM = 4,
  ALLTOALL = 5,
  BARRIER = 6,
  REDUCESCATTER = 7,
  ERROR = 8,
};

inline const char* request_type_name(RequestType t) {
  switch (t) {
    case RequestType::ALLREDUCE: return "ALLREDUCE";
    case RequestType::ALLGATHER: return "ALLGATHER";
    case RequestType::BROADCAST: return "BROADCAST";
    case RequestType::JOIN: return "JOIN";
    case RequestType::ADASUM: return "ADASUM";
    case RequestType::ALLTOALL: return "ALLTOALL";
    case RequestType::BARRIER: return "BARRIER";
    case RequestType::REDUCESCATTER: return "REDUCESCATTER";
  }
  return "?";
}

struct Request {
  int32_t rank = 0;
  RequestType type = RequestType::ALLREDUCE;
  int32_t dtype = 0;
  int32_t element_size = 0;
  int32_t root_rank = -1;
  int32_t group_id = -1;
  std::string name;
  std::vector<int64_t> shape;
  /* ALLTOALL only: how many dim-0 rows this rank sends to each rank
   * (the reference's uneven-splits metadata, operations.cc:1691-1717).
   * Empty = even splits. */
  std::vector<int32_t> splits;
  /* ALLREDUCE family: wire-lowered reduce op + scale factors (the
   * reference Request carries prescale/postscale too, message.h). Checked
   * for cross-rank agreement and echoed on the response so a JOINed rank
   * can reconstruct the identical SPMD program with zero inputs. */
  int32_t reduce_op = -1;
  double prescale = 1.0;
  double postscale = 1.0;
  /* ALLTOALL: digest of the caller's FULL splits matrix (0 = not
   * supplied). Rows legitimately differ per rank, but the matrix every
   * rank derived its row from must be identical — a mismatch must fail on
   * EVERY rank (symmetric ERROR), never hang the subset whose columns
   * happen to agree. */
  int32_t splits_crc = 0;

  int64_t num_elements() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
  int64_t byte_size() const { return num_elements() * element_size; }

  void serialize(Writer& w) const {
    w.i32(rank);
    w.u8(static_cast<uint8_t>(type));
    w.i32(dtype);
    w.i32(element_size);
    w.i32(root_rank);
    w.i32(group_id);
    w.str(name);
    w.u32(static_cast<uint32_t>(shape.size()));
    for (int64_t d : shape) w.i64(d);
    w.u32(static_cast<uint32_t>(splits.size()));
    for (int32_t s : splits) w.i32(s);
    w.i32(reduce_op);
    w.f64(prescale);
    w.f64(postscale);
    w.i32(splits_crc);
  }

  static Request parse(Reader& r) {
    Request q;
    q.rank = r.i32();
    q.type = static_cast<RequestType>(r.u8());
    q.dtype = r.i32();
    q.element_size = r.i32();
    q.root_rank = r.i32();
    q.group_id = r.i32();
    q.name = r.str();
    uint32_t nd = r.u32();
    q.shape.resize(nd);
    for (uint32_t i = 0; i < nd; ++i) q.shape[i] = r.i64();
    uint32_t ns = r.u32();
    q.splits.resize(ns);
    for (uint32_t i = 0; i < ns; ++i) q.splits[i] = r.i32();
    q.reduce_op = r.i32();
    q.prescale = r.f64();
    q.postscale = r.f64();
    q.splits_crc = r.i32();
    return q;
  }
};

struct RequestList {
  std::vector<Request> requests;

  void serialize(Writer& w) const {
    w.u32(static_cast<uint32_t>(requests.size()));
    for (const auto& q : requests) q.serialize(w);
  }
  static RequestList parse(Reader& r) {
    RequestList l;
    uint32_t n = r.u32();
    l.requests.reserve(n);
    for (uint32_t i = 0; i < n; ++i) l.requests.push_back(Request::parse(r));
    return l;
  }
};

struct Response {
  ResponseType type = ResponseType::ALLREDUCE;
  int32_t dtype = 0;
  int32_t root_rank = -1;
  int64_t total_bytes = 0;   // fused payload size (fusion accounting)
  bool from_cache = false;
  std::string error_message;
  std::vector<std::string> tensor_names;
  /* ALLTOALL only: rows this engine's rank receives from each rank — the
   * negotiated metadata the reference exchanges via
   * Controller::AlltoallGetRecvSplits (collective_operations.h:219-221).
   * The one rank-dependent response field (each engine computes its own). */
  std::vector<int32_t> recv_splits;
  /* Per-tensor metadata (aligned with tensor_names) + reduce parameters so
   * a JOINed rank can reconstruct and execute the exact same SPMD program
   * with zero inputs (the reference's JoinOp allocates zero buffers from
   * response metadata, collective_operations.h:275-290). */
  std::vector<std::vector<int64_t>> shapes;
  std::vector<int32_t> group_ids;
  int32_t reduce_op = -1;
  double prescale = 1.0;
  double postscale = 1.0;

  void serialize(Writer& w) const {
    w.u8(static_cast<uint8_t>(type));
    w.i32(dtype);
    w.i32(root_rank);
    w.i64(total_bytes);
    w.u8(from_cache ? 1 : 0);
    w.str(error_message);
    w.u32(static_cast<uint32_t>(tensor_names.size()));
    for (const auto& n : tensor_names) w.str(n);
    w.u32(static_cast<uint32_t>(recv_splits.size()));
    for (int32_t s : recv_splits) w.i32(s);
    w.u32(static_cast<uint32_t>(shapes.size()));
    for (const auto& shp : shapes) {
      w.u32(static_cast<uint32_t>(shp.size()));
      for (int64_t d : shp) w.i64(d);
    }
    w.u32(static_cast<uint32_t>(group_ids.size()));
    for (int32_t g : group_ids) w.i32(g);
    w.i32(reduce_op);
    w.f64(prescale);
    w.f64(postscale);
  }
  static Response parse(Reader& r) {
    Response s;
    s.type = static_cast<ResponseType>(r.u8());
    s.dtype = r.i32();
    s.root_rank = r.i32();
    s.total_bytes = r.i64();
    s.from_cache = r.u8() != 0;
    s.error_message = r.str();
    uint32_t n = r.u32();
    s.tensor_names.reserve(n);
    for (uint32_t i = 0; i < n; ++i) s.tensor_names.push_back(r.str());
    uint32_t ns = r.u32();
    s.recv_splits.resize(ns);
    for (uint32_t i = 0; i < ns; ++i) s.recv_splits[i] = r.i32();
    uint32_t nsh = r.u32();
    s.shapes.resize(nsh);
    for (uint32_t i = 0; i < nsh; ++i) {
      uint32_t nd = r.u32();
      s.shapes[i].resize(nd);
      for (uint32_t j = 0; j < nd; ++j) s.shapes[i][j] = r.i64();
    }
    uint32_t ng = r.u32();
    s.group_ids.resize(ng);
    for (uint32_t i = 0; i < ng; ++i) s.group_ids[i] = r.i32();
    s.reduce_op = r.i32();
    s.prescale = r.f64();
    s.postscale = r.f64();
    return s;
  }
};

struct ResponseList {
  std::vector<Response> responses;

  void serialize(Writer& w) const {
    w.u32(static_cast<uint32_t>(responses.size()));
    for (const auto& s : responses) s.serialize(w);
  }
};

}  // namespace hvd

#endif  // HVD_MESSAGE_H
