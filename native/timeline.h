/* Chrome-trace timeline writer.
 *
 * TPU-native rebuild of the reference Timeline
 * (/root/reference/horovod/common/timeline.h:48-100, timeline.cc): a
 * dedicated writer thread consumes a bounded queue of records and emits
 * Chrome trace-event JSON (catapult "Trace Event Format"). The reference
 * uses a 1M-entry boost lock-free SPSC queue; a mutex + condvar deque is
 * equivalent here (producers are a handful of Python threads, the bound
 * guards memory the same way).
 */

#ifndef HVD_TIMELINE_H
#define HVD_TIMELINE_H

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

namespace hvd {

class Timeline {
 public:
  ~Timeline() { stop(); }

  /* Open `path` and start the writer thread. Returns 0, -1 on IO error. */
  int32_t start(const std::string& path);

  /* Flush and close. Idempotent. */
  void stop();

  bool active() const { return active_; }

  /* phase: 0 begin ("B"), 1 end ("E"), 2 instant ("i").
   * timestamp_us < 0 means "stamp with the engine's own clock". */
  void record(const std::string& tensor, const std::string& activity,
              int32_t phase, int64_t timestamp_us);

 private:
  struct Record {
    std::string tensor;
    std::string activity;
    int32_t phase;
    int64_t ts_us;
  };

  static constexpr size_t kMaxQueue = 1 << 20;  // reference: 1M records

  void writer_loop();
  void write_record(const Record& r);
  int64_t lane_of(const std::string& tensor);

  std::ofstream out_;
  bool active_ = false;
  bool first_event_ = true;
  std::thread writer_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Record> queue_;
  bool shutdown_ = false;
  std::unordered_map<std::string, int64_t> lanes_;
  int64_t next_lane_ = 1;
};

}  // namespace hvd

#endif  // HVD_TIMELINE_H
