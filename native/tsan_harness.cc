/* ThreadSanitizer harness for the native dynamic engine.
 *
 * hvdsched model-checks the *Python* concurrency core on a cooperative
 * seam; the native engine's real pthreads (the timeline writer thread,
 * plus whatever threads the embedder drives the C API from) are outside
 * that seam. This harness drives the documented concurrency contract of
 * hvd_core.h hard from real threads so `ci.sh` can run it under
 * -fsanitize=thread: any data race in engine.cc/timeline.cc is a CI
 * failure, not a once-a-month loopback heisencrash.
 *
 * Thread roles mirror the Python embedding (one world of 2 ranks as two
 * engines in-process, the loopback shape):
 *   - N submitter threads: enqueue/abandon named tensors on BOTH rank
 *     engines (rank-symmetric, so negotiation completes);
 *   - 1 negotiator thread: the per-cycle pop -> rank-ordered ingest ->
 *     compute_responses -> cache-bits AND -> commit loop. It is the only
 *     thread touching the pop/resp/bits out-buffer slots, per the
 *     header's "valid until the next call on the same engine from the
 *     same thread" ownership rule;
 *   - 1 watchdog thread: stall_report + introspection (its out-buffer
 *     slot is its own);
 *   - M recorder threads: hammer hvd_timeline_record while the main
 *     thread cycles hvd_timeline_start/stop underneath them.
 *
 * Also asserts the symmetric-negotiation invariant while it runs: both
 * engines must compute byte-identical response lists every cycle.
 */

#include <atomic>
#include <cassert>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "hvd_core.h"

namespace {

constexpr int kWorld = 2;
constexpr int kSubmitters = 2;
constexpr int kRecorders = 2;
constexpr int kItersPerSubmitter = 120;

hvd_engine_t g_engine[kWorld];
std::atomic<int> g_submitters_done{0};
std::atomic<bool> g_stop_aux{false};
std::atomic<long> g_cycles{0};
std::atomic<long> g_responses_checked{0};
std::atomic<long> g_records{0};

void submitter(int sid) {
  int64_t shape[1] = {16};
  for (int i = 0; i < kItersPerSubmitter; ++i) {
    std::string name = "g" + std::to_string(sid) + "_" + std::to_string(i);
    int type = (i % 5 == 0) ? HVD_REQ_BROADCAST : HVD_REQ_ALLREDUCE;
    for (int r = 0; r < kWorld; ++r) {
      int32_t rc = hvd_engine_enqueue(
          g_engine[r], name.c_str(), type, /*dtype=*/0, /*element_size=*/4,
          shape, /*ndim=*/1, /*root_rank=*/0, /*group_id=*/-1,
          /*splits=*/nullptr, /*nsplits=*/0, /*reduce_op=*/0,
          /*prescale=*/1.0, /*postscale=*/1.0, /*splits_crc=*/0);
      assert(rc >= -2);
      (void)rc;
    }
    if (i % 7 == 3) {
      // symmetric retry-after-timeout shape: both ranks abandon, so the
      // name either never went out (cleanly dropped) or completes as a
      // normal table entry; rc -1 (already completed) is fine
      for (int r = 0; r < kWorld; ++r) {
        hvd_engine_abandon(g_engine[r], name.c_str());
      }
    }
    hvd_timeline_record(g_engine[0], name.c_str(), "ENQUEUE", 2, -1);
    if (i % 16 == 0) std::this_thread::yield();
  }
  g_submitters_done.fetch_add(1);
}

void negotiator() {
  std::vector<uint8_t> pops[kWorld];
  std::vector<uint8_t> resp0;
  for (;;) {
    bool drained = g_submitters_done.load() == kSubmitters;
    // pop every rank first (copy out: ingest on the same engine re-enters
    // the lock and the next pop invalidates the slot)
    for (int r = 0; r < kWorld; ++r) {
      const uint8_t* buf = nullptr;
      size_t len = 0;
      hvd_engine_pop_requests(g_engine[r], &buf, &len);
      pops[r].assign(buf, buf + len);
    }
    for (int r = 0; r < kWorld; ++r) {
      for (int src = 0; src < kWorld; ++src) {
        hvd_engine_ingest(g_engine[r], src, pops[src].data(),
                          pops[src].size());
      }
    }
    for (int r = 0; r < kWorld; ++r) {
      const uint8_t* buf = nullptr;
      size_t len = 0;
      hvd_engine_compute_responses(g_engine[r], &buf, &len);
      if (r == 0) {
        resp0.assign(buf, buf + len);
      } else {
        // symmetric negotiation: identical inputs in rank order must
        // yield byte-identical plans on every member
        assert(len == resp0.size() &&
               std::memcmp(buf, resp0.data(), len) == 0);
        g_responses_checked.fetch_add(1);
      }
    }
    // response-cache coordination round: AND the bit vectors, commit
    const uint8_t* bits[kWorld];
    size_t blen[kWorld];
    std::vector<uint8_t> anded;
    for (int r = 0; r < kWorld; ++r) {
      hvd_engine_cache_bits(g_engine[r], &bits[r], &blen[r]);
    }
    size_t n = blen[0] < blen[1] ? blen[0] : blen[1];
    anded.resize(n);
    for (size_t i = 0; i < n; ++i) anded[i] = bits[0][i] & bits[1][i];
    for (int r = 0; r < kWorld; ++r) {
      hvd_engine_commit_cache_bits(g_engine[r], anded.data(), anded.size());
    }
    long c = g_cycles.fetch_add(1) + 1;
    if (drained && hvd_engine_pending_count(g_engine[0]) == 0 &&
        hvd_engine_pending_count(g_engine[1]) == 0) {
      return;
    }
    if (c > 200000) {
      std::fprintf(stderr, "tsan harness: negotiation never drained\n");
      std::abort();
    }
    if (c % 64 == 0) std::this_thread::yield();
  }
}

void watchdog() {
  while (!g_stop_aux.load()) {
    for (int r = 0; r < kWorld; ++r) {
      const uint8_t* buf = nullptr;
      size_t len = 0;
      hvd_engine_stall_report(g_engine[r], &buf, &len);
      hvd_engine_pending_count(g_engine[r]);
      hvd_engine_cache_size(g_engine[r]);
      hvd_engine_cache_has(g_engine[r], "g0_0");
      hvd_engine_join_pending(g_engine[r]);
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

void recorder(int rid) {
  int i = 0;
  while (!g_stop_aux.load()) {
    std::string tensor = "lane" + std::to_string(rid);
    // engine 1's timeline is never started: records there must be cheap
    // inactive no-ops, and racing them against start/stop is the point
    hvd_timeline_record(g_engine[0], tensor.c_str(), "CYCLE", 0, -1);
    hvd_timeline_record(g_engine[0], tensor.c_str(), "CYCLE", 1, -1);
    hvd_timeline_record(g_engine[1], tensor.c_str(), "IDLE", 2, -1);
    g_records.fetch_add(3);
    if (++i % 32 == 0) std::this_thread::yield();
  }
}

}  // namespace

int main(int argc, char** argv) {
  const char* tl_path =
      argc > 1 ? argv[1] : "/tmp/hvd_tsan_timeline.json";
  for (int r = 0; r < kWorld; ++r) {
    g_engine[r] = hvd_engine_create(kWorld, r, /*fusion_threshold=*/1 << 20,
                                    /*cache_capacity=*/64,
                                    /*stall_warn=*/0.05,
                                    /*stall_shutdown=*/0.0);
    assert(g_engine[r] != nullptr);
  }
  assert(hvd_timeline_start(g_engine[0], tl_path) == 0);

  std::vector<std::thread> aux;
  aux.emplace_back(watchdog);
  for (int i = 0; i < kRecorders; ++i) aux.emplace_back(recorder, i);
  std::vector<std::thread> subs;
  for (int i = 0; i < kSubmitters; ++i) subs.emplace_back(submitter, i);
  std::thread neg(negotiator);

  // cycle the timeline under live recorders: stop/start is the race the
  // writer thread's shutdown handshake must survive
  for (int i = 0; i < 3; ++i) {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    hvd_timeline_stop(g_engine[0]);
    assert(hvd_timeline_start(g_engine[0], tl_path) == 0);
  }

  for (auto& t : subs) t.join();
  neg.join();
  g_stop_aux.store(true);
  for (auto& t : aux) t.join();
  hvd_timeline_stop(g_engine[0]);
  for (int r = 0; r < kWorld; ++r) hvd_engine_destroy(g_engine[r]);

  std::printf(
      "tsan harness OK: %ld cycles, %ld identical cross-rank response "
      "lists, %ld timeline records, %d tensors/submitter x %d "
      "submitters (engine %s)\n",
      g_cycles.load(), g_responses_checked.load(), g_records.load(),
      kItersPerSubmitter, kSubmitters, hvd_core_version());
  return 0;
}
