#include "timeline.h"

#include <chrono>
#include <cstdio>

namespace hvd {
namespace {

int64_t now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

}  // namespace

int32_t Timeline::start(const std::string& path) {
  std::lock_guard<std::mutex> lock(mu_);
  if (active_) return 0;
  out_.open(path, std::ios::out | std::ios::trunc);
  if (!out_.is_open()) return -1;
  out_ << "[\n";
  first_event_ = true;
  shutdown_ = false;
  active_ = true;
  writer_ = std::thread([this] { writer_loop(); });
  return 0;
}

void Timeline::stop() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (!active_) return;
    shutdown_ = true;
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  std::lock_guard<std::mutex> lock(mu_);
  out_ << "\n]\n";
  out_.close();
  active_ = false;
}

void Timeline::record(const std::string& tensor, const std::string& activity,
                      int32_t phase, int64_t timestamp_us) {
  std::lock_guard<std::mutex> lock(mu_);
  if (!active_ || queue_.size() >= kMaxQueue) return;  // drop when full
  queue_.push_back(Record{tensor, activity, phase,
                          timestamp_us >= 0 ? timestamp_us : now_us()});
  cv_.notify_one();
}

void Timeline::writer_loop() {
  std::unique_lock<std::mutex> lock(mu_);
  for (;;) {
    cv_.wait(lock, [this] { return !queue_.empty() || shutdown_; });
    while (!queue_.empty()) {
      Record r = std::move(queue_.front());
      queue_.pop_front();
      write_record(r);
    }
    if (shutdown_) return;
  }
}

void Timeline::write_record(const Record& r) {
  // Called with mu_ held (writer thread only). Resolve the lane first: a
  // new tensor emits its thread_name metadata record, which must be a
  // complete record of its own, not spliced into the middle of this one.
  int64_t lane = lane_of(r.tensor);
  const char* ph = r.phase == 0 ? "B" : (r.phase == 1 ? "E" : "i");
  if (!first_event_) out_ << ",\n";
  first_event_ = false;
  out_ << "{\"name\": \"" << json_escape(r.activity) << "\", \"cat\": \""
       << json_escape(r.tensor) << "\", \"ph\": \"" << ph
       << "\", \"ts\": " << r.ts_us << ", \"pid\": 0, \"tid\": " << lane;
  if (r.phase == 2) out_ << ", \"s\": \"t\"";
  out_ << "}";
}

int64_t Timeline::lane_of(const std::string& tensor) {
  auto it = lanes_.find(tensor);
  if (it != lanes_.end()) return it->second;
  int64_t lane = next_lane_++;
  lanes_.emplace(tensor, lane);
  // name the lane after the tensor so the trace viewer shows one row per
  // tensor, like the reference's per-tensor timeline rows
  if (!first_event_) out_ << ",\n";
  first_event_ = false;
  out_ << "{\"name\": \"thread_name\", \"ph\": \"M\", \"pid\": 0, \"tid\": "
       << lane << ", \"args\": {\"name\": \"" << json_escape(tensor)
       << "\"}}";
  return lane;
}

}  // namespace hvd
