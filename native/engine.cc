/* The dynamic engine: request queue, readiness negotiation, response cache,
 * fusion planning, and stall detection.
 *
 * TPU-native rebuild of the reference's core runtime components:
 *   - TensorQueue            (tensor_queue.cc, duplicate-name detection at
 *                             common.h:229-232)
 *   - Controller bookkeeping (controller.cc:73-430 ComputeResponseList,
 *                             IncrementTensorCount readiness table,
 *                             ConstructResponse shape/dtype mismatch ERRORs,
 *                             FuseResponses fusion packing)
 *   - ResponseCache          (response_cache.cc LRU + bitvector
 *                             coordination, response_cache.h:50,107-169)
 *   - GroupTable             (group_table.cc, enforced joint fusion at
 *                             controller.cc:213-237)
 *   - StallInspector         (stall_inspector.cc, warn/shutdown thresholds
 *                             at stall_inspector.h:71-86)
 *
 * Execution is NOT here: XLA runs the collectives. Every rank feeds the
 * identical rank-ordered request lists into ingest() and deterministically
 * computes the same fused response plan — the symmetric degeneration of the
 * reference's rank-0 master protocol (controller.h:72-108) natural on a TPU
 * mesh where the transport is an allgather.
 */

#include "hvd_core.h"

#include <algorithm>
#include <chrono>
#include <list>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

#include "message.h"
#include "timeline.h"
#include "wire.h"

namespace hvd {
namespace {

double now_seconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::string shape_to_string(const std::vector<int64_t>& shape) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < shape.size(); ++i) {
    if (i) os << ", ";
    os << shape[i];
  }
  os << "]";
  return os.str();
}

/* ---------------------------------------------------------------- cache */

struct TensorParams {
  int32_t dtype = 0;
  int32_t root_rank = -1;
  uint8_t type = 0;
  std::vector<int64_t> shape;

  bool operator==(const TensorParams& o) const {
    return dtype == o.dtype && root_rank == o.root_rank && type == o.type &&
           shape == o.shape;
  }
};

/* LRU cache of prior responses (response_cache.h). A HIT lets ranks skip
 * full negotiation for tensors whose metadata is unchanged — coordinated
 * via a bitvector AND across ranks. */
class ResponseCache {
 public:
  enum class State { MISS, HIT, INVALID };

  void set_capacity(uint32_t cap) {
    capacity_ = cap;
    while (lru_.size() > capacity_) evict_lru();
  }
  uint32_t capacity() const { return capacity_; }
  size_t size() const { return lru_.size(); }

  State cached(const Request& q) const {
    auto it = index_.find(q.name);
    if (it == index_.end()) return State::MISS;
    const Entry& e = *it->second;
    TensorParams p{q.dtype, q.root_rank, static_cast<uint8_t>(q.type),
                   q.shape};
    return e.params == p ? State::HIT : State::INVALID;
  }

  void put(const Request& q, const Response& resp) {
    if (capacity_ == 0) return;
    auto it = index_.find(q.name);
    if (it != index_.end()) {
      lru_.erase(it->second);
      index_.erase(it);
    }
    while (lru_.size() >= capacity_) evict_lru();
    lru_.push_front(Entry{
        q.name,
        TensorParams{q.dtype, q.root_rank, static_cast<uint8_t>(q.type),
                     q.shape},
        resp});
    index_[q.name] = lru_.begin();
    bits_dirty_ = true;
  }

  void erase(const std::string& name) {
    auto it = index_.find(name);
    if (it == index_.end()) return;
    lru_.erase(it->second);
    index_.erase(it);
    bits_dirty_ = true;
  }

  bool has(const std::string& name) const { return index_.count(name) != 0; }

  /* Touch as most-recently-used. */
  void touch(const std::string& name) {
    auto it = index_.find(name);
    if (it == index_.end()) return;
    lru_.splice(lru_.begin(), lru_, it->second);
    bits_dirty_ = true;
  }

  /* Stable bit position per cached name for the coordination bitvector
   * (update_cache_bits, response_cache.cc). Recomputed lazily: position =
   * LRU order at computation time; identical on every rank because every
   * rank applies identical put/erase/touch sequences. */
  int32_t bit_of(const std::string& name) {
    refresh_bits();
    auto it = bit_index_.find(name);
    return it == bit_index_.end() ? -1 : it->second;
  }

  const Response* response_at_bit(int32_t bit) {
    refresh_bits();
    if (bit < 0 || bit >= static_cast<int32_t>(bit_names_.size()))
      return nullptr;
    auto it = index_.find(bit_names_[bit]);
    return it == index_.end() ? nullptr : &it->second->response;
  }

  size_t num_bits() {
    refresh_bits();
    return bit_names_.size();
  }

 private:
  struct Entry {
    std::string name;
    TensorParams params;
    Response response;
  };

  void evict_lru() {
    if (lru_.empty()) return;
    index_.erase(lru_.back().name);
    lru_.pop_back();
    bits_dirty_ = true;
  }

  void refresh_bits() {
    if (!bits_dirty_) return;
    bit_index_.clear();
    bit_names_.clear();
    int32_t i = 0;
    for (const auto& e : lru_) {
      bit_index_[e.name] = i++;
      bit_names_.push_back(e.name);
    }
    bits_dirty_ = false;
  }

  uint32_t capacity_ = 1024;
  std::list<Entry> lru_;
  std::unordered_map<std::string, std::list<Entry>::iterator> index_;
  std::unordered_map<std::string, int32_t> bit_index_;
  std::vector<std::string> bit_names_;
  bool bits_dirty_ = true;
};

/* ------------------------------------------------------------ the engine */

class Engine {
 public:
  Engine(int32_t world_size, int32_t rank, int64_t fusion_threshold,
         int32_t cache_capacity, double stall_warn, double stall_shutdown)
      : world_size_(world_size),
        rank_(rank),
        fusion_threshold_(fusion_threshold),
        stall_warn_(stall_warn),
        stall_shutdown_(stall_shutdown) {
    cache_.set_capacity(static_cast<uint32_t>(cache_capacity));
  }

  int32_t enqueue(const char* name, int32_t request_type, int32_t dtype,
                  int32_t element_size, const int64_t* shape, int32_t ndim,
                  int32_t root_rank, int32_t group_id,
                  const int32_t* splits, int32_t nsplits,
                  int32_t reduce_op, double prescale, double postscale,
                  int32_t splits_crc) {
    std::lock_guard<std::mutex> lock(mu_);
    std::string key(name);
    if (outstanding_.count(key)) return -1;  // duplicate name still in flight
    Request q;
    q.rank = rank_;
    q.type = static_cast<RequestType>(request_type);
    q.dtype = dtype;
    q.element_size = element_size;
    q.root_rank = root_rank;
    q.group_id = group_id;
    q.reduce_op = reduce_op;
    q.prescale = prescale;
    q.postscale = postscale;
    q.splits_crc = splits_crc;
    q.name = std::move(key);
    q.shape.assign(shape, shape + ndim);
    if (splits != nullptr && nsplits > 0) q.splits.assign(splits, splits + nsplits);
    /* Splits validation mirrors EnqueueTensorAlltoall
     * (operations.cc:1691-1727): right length, non-negative, sum within
     * the tensor's first dimension. */
    if (!q.splits.empty()) {
      if (q.type != RequestType::ALLTOALL) return -3;
      if (static_cast<int32_t>(q.splits.size()) != world_size_) return -3;
      int64_t sum = 0;
      for (int32_t s : q.splits) {
        if (s < 0) return -3;
        sum += s;
      }
      if (!q.shape.empty() && sum > q.shape[0]) return -3;
    }
    /* Retry after abandon(): if this rank's original submission is still
     * being negotiated globally (table entry with our rank ready), do NOT
     * emit a second wire request — every rank would grow a ghost table
     * entry no one else ever joins. Re-attach instead: the in-flight
     * negotiation completes this name normally. The retry must carry the
     * same metadata as the in-flight request — re-attaching never passes
     * through ingest()'s validate(), so a silent mismatch would defeat the
     * negotiation layer's core guarantee. */
    auto it = table_.find(q.name);
    if (it != table_.end() && it->second.ready_ranks.count(rank_)) {
      const TableEntry& entry = it->second;
      const Request& orig = entry.first;
      if (q.type != orig.type || q.dtype != orig.dtype ||
          q.root_rank != orig.root_rank) {
        return -2;  // metadata differs from the in-flight negotiation
      }
      bool dims_after_first = q.type == RequestType::ALLGATHER ||
                              q.type == RequestType::ALLTOALL;
      if (dims_after_first) {
        /* dim0 is per-rank for gather/alltoall; compare rank-local dim0
         * (recorded at ingest) and the shared trailing dims. */
        bool ok = q.shape.size() == orig.shape.size();
        for (size_t i = 1; ok && i < q.shape.size(); ++i)
          ok = q.shape[i] == orig.shape[i];
        if (q.type == RequestType::ALLTOALL ||
            q.type == RequestType::ALLGATHER) {
          auto dit = entry.dim0_by_rank.find(rank_);
          int64_t d0 = q.shape.empty() ? 0 : q.shape[0];
          ok = ok && (dit == entry.dim0_by_rank.end() || dit->second == d0);
        }
        if (!ok) return -2;
      } else if (q.shape != orig.shape) {
        return -2;
      }
      /* Splits are rank-local too: a retry must match THIS rank's
       * in-flight row (recorded in splits_by_rank), not rank 0's — other
       * ranks' recv_splits were computed from the original row, so a
       * silent change would misroute data. */
      auto sit = entry.splits_by_rank.find(rank_);
      const std::vector<int32_t> no_splits;
      const std::vector<int32_t>& orig_splits =
          sit == entry.splits_by_rank.end() ? no_splits : sit->second;
      if (q.splits != orig_splits) return -2;
      outstanding_.insert(q.name);
      local_inflight_[q.name] = std::move(q);
      return 1;  // re-attached to in-flight negotiation
    }
    outstanding_.insert(q.name);
    pending_.push_back(std::move(q));
    return 0;
  }

  int32_t pop_requests(const uint8_t** out, size_t* out_len) {
    std::lock_guard<std::mutex> lock(mu_);
    RequestList list;
    list.requests = std::move(pending_);
    pending_.clear();
    // Track locally submitted requests awaiting a response plan; cache
    // lookups and the stall inspector key off this set.
    for (auto& q : list.requests) {
      local_inflight_[q.name] = q;
    }
    Writer w;
    list.serialize(w);
    pop_buf_ = std::move(w.buf);
    *out = pop_buf_.data();
    *out_len = pop_buf_.size();
    return 0;
  }

  int32_t ingest(int32_t rank, const uint8_t* data, size_t len) {
    std::lock_guard<std::mutex> lock(mu_);
    Reader r(data, len);
    RequestList list;
    try {
      list = RequestList::parse(r);
    } catch (const std::exception&) {
      return -1;
    }
    double now = now_seconds();
    for (auto& q : list.requests) {
      if (q.type == RequestType::JOIN) {
        joined_ranks_.insert(rank);
        join_names_.insert(q.name);
        last_joined_rank_ = rank;  // rank-ordered ingest: deterministic
        join_pending_ = true;
        continue;
      }
      /* Served this cycle from the cache (commit runs pre-ingest under
       * the batched transport): the request is already satisfied — do not
       * grow a table entry for it. Identical served sets everywhere keep
       * this symmetric. */
      if (served_this_cycle_.count(q.name)) continue;
      /* Cache invalidation must be driven by the globally-ingested request
       * stream, not by this rank's local inflight set: every rank ingests
       * the identical rank-ordered lists, so erases happen on the same
       * cycle everywhere and the lazily-recomputed bit positions stay
       * aligned (the reference syncs invalid bits across workers for the
       * same reason, response_cache.h:149-151 CacheCoordinator). */
      if (q.type != RequestType::BARRIER &&
          cache_.cached(q) == ResponseCache::State::INVALID) {
        cache_.erase(q.name);
      }
      auto it = table_.find(q.name);
      if (it == table_.end()) {
        TableEntry e;
        e.first = q;
        e.first_rank = rank;
        e.ready_ranks.insert(rank);
        e.first_seen = now;
        e.sequence = next_sequence_++;
        if (!q.splits.empty()) e.splits_by_rank[rank] = q.splits;
        if (q.type == RequestType::ALLTOALL ||
            q.type == RequestType::ALLGATHER)
          e.dim0_by_rank[rank] = q.shape.empty() ? 0 : q.shape[0];
        table_.emplace(q.name, std::move(e));
      } else {
        TableEntry& e = it->second;
        validate(e, q, rank);
        if (!q.splits.empty()) e.splits_by_rank[rank] = q.splits;
        if (q.type == RequestType::ALLTOALL ||
            q.type == RequestType::ALLGATHER)
          e.dim0_by_rank[rank] = q.shape.empty() ? 0 : q.shape[0];
        e.ready_ranks.insert(rank);
      }
    }
    return 0;
  }

  int32_t cache_bits(const uint8_t** out, size_t* out_len) {
    std::lock_guard<std::mutex> lock(mu_);
    size_t nbits = cache_.num_bits();
    bits_buf_.assign((nbits + 7) / 8, 0);
    for (const auto& kv : local_inflight_) {
      const Request& q = kv.second;
      if (q.type == RequestType::BARRIER || q.type == RequestType::JOIN)
        continue;  // never cached (controller.cc:100-104)
      if (!q.splits.empty())
        continue;  // uneven alltoall: recv_splits vary per call, never HIT
      if (q.type == RequestType::ALLGATHER)
        continue;  /* per-rank first dims are per-call runtime data a rank
                    * cannot vouch for alone (another rank's dim may have
                    * changed while this rank's bit says HIT) */
      if (cache_.cached(q) == ResponseCache::State::HIT) {
        int32_t bit = cache_.bit_of(q.name);
        if (bit >= 0) bits_buf_[bit / 8] |= (1u << (bit % 8));
      }
    }
    *out = bits_buf_.data();
    *out_len = bits_buf_.size();
    return 0;
  }

  int32_t commit_cache_bits(const uint8_t* bits, size_t len) {
    std::lock_guard<std::mutex> lock(mu_);
    cache_hits_this_cycle_.clear();
    served_this_cycle_.clear();
    std::vector<std::string> served;
    for (auto& kv : local_inflight_) {
      const Request& q = kv.second;
      if (!q.splits.empty()) continue;  // uneven alltoall never cache-served
      if (q.type == RequestType::ALLGATHER) continue;  // see cache_bits()
      /* INVALID entries were already erased during ingest() — driven by
       * the global request stream so every rank erased identically; a
       * local-only erase here would desynchronize bit positions. */
      auto state = cache_.cached(q);
      if (state != ResponseCache::State::HIT) continue;
      int32_t bit = cache_.bit_of(q.name);
      bool global_hit = bit >= 0 &&
                        static_cast<size_t>(bit / 8) < len &&
                        (bits[bit / 8] >> (bit % 8)) & 1;
      if (global_hit) {
        const Response* resp = cache_.response_at_bit(bit);
        if (resp != nullptr) {
          Response r = *resp;
          r.from_cache = true;
          cache_hits_this_cycle_.push_back(std::move(r));
          served.push_back(q.name);
        }
      }
    }
    for (const auto& name : served) {
      cache_.touch(name);
      complete(name);
      /* A cache-served tensor must not also be scheduled from the
       * negotiation table. Commit now runs BEFORE ingest (batched one-
       * round transport: bits are computed against the pre-ingest cache
       * state so bit positions agree on every rank), so this erase covers
       * prior-cycle entries and served_this_cycle_ makes ingest skip this
       * cycle's requests for served names. The served set is identical on
       * every rank (AND of identical bit layouts), so both stay
       * consistent. */
      table_.erase(name);
      served_this_cycle_.insert(name);
    }
    return 0;
  }

  int32_t compute_responses(const uint8_t** out, size_t* out_len) {
    std::lock_guard<std::mutex> lock(mu_);
    ResponseList result;

    // cache-served responses first (fast path)
    for (auto& r : cache_hits_this_cycle_) result.responses.push_back(std::move(r));
    cache_hits_this_cycle_.clear();

    // collect table entries that are ready on every (non-joined) rank
    std::vector<const TableEntry*> ready;
    std::vector<Response> errors;
    for (auto& kv : table_) {
      TableEntry& e = kv.second;
      if (!e.error_message.empty()) {
        if (all_ranks_in(e)) {
          Response err;
          err.type = ResponseType::ERROR;
          err.error_message = e.error_message;
          err.tensor_names = {e.first.name};
          errors.push_back(std::move(err));
          e.done = true;
        }
        continue;
      }
      if (all_ranks_in(e)) ready.push_back(&e);
    }
    std::sort(ready.begin(), ready.end(),
              [](const TableEntry* a, const TableEntry* b) {
                return a->sequence < b->sequence;
              });

    // group-table constraint: a grouped tensor may only be scheduled when
    // its whole group is ready (controller.cc:213-237)
    std::map<int32_t, std::vector<const TableEntry*>> groups;
    for (const TableEntry* e : ready) {
      if (e->first.group_id >= 0) groups[e->first.group_id].push_back(e);
    }

    std::vector<const TableEntry*> schedulable;
    for (const TableEntry* e : ready) {
      int32_t g = e->first.group_id;
      if (g < 0) {
        schedulable.push_back(e);
        continue;
      }
      size_t expected = group_member_counts_.count(g)
                            ? group_member_counts_[g]
                            : groups[g].size();
      if (groups[g].size() >= expected) schedulable.push_back(e);
    }

    fuse(schedulable, result);
    for (auto& err : errors) result.responses.push_back(std::move(err));

    // JOIN: emitted only when every rank joined (controller.cc:268-272);
    // root_rank carries the last joined rank (the reference's
    // output_last_joined_rank, operations.cc:1729-1761)
    if (join_pending_ &&
        joined_ranks_.size() == static_cast<size_t>(world_size_)) {
      Response j;
      j.type = ResponseType::JOIN;
      j.root_rank = last_joined_rank_;
      j.tensor_names.assign(join_names_.begin(), join_names_.end());
      for (const auto& n : j.tensor_names) complete(n);
      result.responses.push_back(std::move(j));
      joined_ranks_.clear();
      join_names_.clear();
      join_pending_ = false;
    }

    // mark scheduled tensors complete + populate the cache (uneven
    // alltoalls stay uncached: their recv_splits are call-specific)
    for (const TableEntry* e : schedulable) {
      if (e->first.type != RequestType::BARRIER &&
          e->first.type != RequestType::ALLGATHER &&
          e->splits_by_rank.empty()) {
        Response proto;
        proto.type = static_cast<ResponseType>(e->first.type);
        proto.dtype = e->first.dtype;
        proto.root_rank = e->first.root_rank;
        proto.total_bytes = e->first.byte_size();
        proto.tensor_names = {e->first.name};
        /* joined-rank zero reconstruction must work from cache-served
         * responses too */
        proto.shapes = {e->first.shape};
        proto.group_ids = {e->first.group_id};
        proto.reduce_op = e->first.reduce_op;
        proto.prescale = e->first.prescale;
        proto.postscale = e->first.postscale;
        cache_.put(e->first, proto);
      }
    }
    std::vector<std::string> done_names;
    for (const TableEntry* e : schedulable) done_names.push_back(e->first.name);
    for (auto& kv : table_) {
      if (kv.second.done) done_names.push_back(kv.first);
    }
    for (const auto& n : done_names) {
      table_.erase(n);
      complete(n);
    }

    Writer w;
    result.serialize(w);
    resp_buf_ = std::move(w.buf);
    *out = resp_buf_.data();
    *out_len = resp_buf_.size();
    return 0;
  }

  int32_t stall_report(const uint8_t** out, size_t* out_len) {
    std::lock_guard<std::mutex> lock(mu_);
    double now = now_seconds();
    Writer w;
    uint32_t count = 0;
    Writer body;
    bool shutdown = false;
    for (const auto& kv : table_) {
      const TableEntry& e = kv.second;
      double waited = now - e.first_seen;
      if (!e.ready_ranks.empty() && !all_ranks_in(e) && waited > stall_warn_) {
        body.str(kv.first);
        body.u32(static_cast<uint32_t>(e.ready_ranks.size()));
        for (int32_t r : e.ready_ranks) body.u32(static_cast<uint32_t>(r));
        body.f64(waited);
        ++count;
        if (stall_shutdown_ > 0 && waited > stall_shutdown_) shutdown = true;
      }
    }
    w.u32(count);
    w.buf.insert(w.buf.end(), body.buf.begin(), body.buf.end());
    stall_buf_ = std::move(w.buf);
    *out = stall_buf_.data();
    *out_len = stall_buf_.size();
    return shutdown ? 1 : 0;
  }

  void register_group(int32_t group_id, size_t n_members) {
    std::lock_guard<std::mutex> lock(mu_);
    group_member_counts_[group_id] = n_members;
  }

  /* Abandon a locally-submitted request after a negotiation timeout so the
   * name can be retried (the reference has no analog: its waits are
   * unbounded). Clears local bookkeeping only; the shared table entry (if
   * the request already went out) completes or stalls globally. */
  int32_t abandon(const char* name) {
    std::lock_guard<std::mutex> lock(mu_);
    std::string key(name);
    if (!outstanding_.count(key)) return -1;
    complete(key);
    return 0;
  }

  int32_t pending_count() {
    std::lock_guard<std::mutex> lock(mu_);
    return static_cast<int32_t>(pending_.size() + local_inflight_.size());
  }
  int32_t cache_size() {
    std::lock_guard<std::mutex> lock(mu_);
    return static_cast<int32_t>(cache_.size());
  }

  /* Whether `name` is currently held by the response cache. Invalidation
   * is driven by the globally-ingested request stream (see ingest()), so
   * every rank answers identically on the same cycle — the coordinator
   * ResponseCache (engine_service) gates its local serving on this to
   * stay coherent with the protocol-level cache. */
  int32_t cache_has(const char* name) {
    std::lock_guard<std::mutex> lock(mu_);
    return cache_.has(name) ? 1 : 0;
  }

  /* Whether any rank is currently JOINed (its JOIN request ingested but
   * not yet completed by every rank joining). While true, peers must not
   * short-circuit negotiation from caches: the joined rank only learns
   * about scheduled collectives (for its zero executions) from responses
   * computed by a real round. */
  int32_t join_pending() {
    std::lock_guard<std::mutex> lock(mu_);
    return (join_pending_ || !joined_ranks_.empty()) ? 1 : 0;
  }

  Timeline timeline;

 private:
  struct TableEntry {
    Request first;
    int32_t first_rank = 0;
    std::set<int32_t> ready_ranks;
    double first_seen = 0;
    uint64_t sequence = 0;
    bool done = false;
    std::string error_message;
    /* ALLTOALL: each rank's submitted uneven splits row (absent = even).
     * The transpose column for this engine's rank becomes the response's
     * recv_splits (AlltoallGetRecvSplits analog). */
    std::map<int32_t, std::vector<int32_t>> splits_by_rank;
    std::map<int32_t, int64_t> dim0_by_rank;
  };

  bool all_ranks_in(const TableEntry& e) const {
    // joined ranks count as implicitly ready for every tensor
    size_t effective = e.ready_ranks.size();
    for (int32_t r : joined_ranks_)
      if (!e.ready_ranks.count(r)) ++effective;
    return effective >= static_cast<size_t>(world_size_);
  }

  /* Mismatch checks mirroring ConstructResponse (controller.cc): two ranks
   * submitting the same name with different type/dtype/shape is a user
   * error answered with an informative ERROR response, not an abort. */
  void validate(TableEntry& e, const Request& q, int32_t rank) {
    if (!e.error_message.empty()) return;
    std::ostringstream os;
    if (q.type != e.first.type) {
      os << "Mismatched collective operations: rank " << e.first_rank
         << " performed " << request_type_name(e.first.type) << " on tensor "
         << e.first.name << " while rank " << rank << " performed "
         << request_type_name(q.type) << ".";
      e.error_message = os.str();
      return;
    }
    if (q.dtype != e.first.dtype) {
      os << "Mismatched data types: rank " << e.first_rank
         << " submitted tensor " << e.first.name << " with dtype id "
         << e.first.dtype << " while rank " << rank << " submitted dtype id "
         << q.dtype << ".";
      e.error_message = os.str();
      return;
    }
    bool shape_must_match = q.type == RequestType::ALLREDUCE ||
                            q.type == RequestType::ADASUM ||
                            q.type == RequestType::BROADCAST ||
                            q.type == RequestType::REDUCESCATTER;
    bool dims_after_first_must_match = q.type == RequestType::ALLGATHER ||
                                       q.type == RequestType::ALLTOALL;
    if (shape_must_match && q.shape != e.first.shape) {
      os << "Mismatched " << request_type_name(q.type) << " tensor shapes: "
         << "rank " << e.first_rank << " submitted " << e.first.name
         << " with shape " << shape_to_string(e.first.shape) << " while rank "
         << rank << " submitted shape " << shape_to_string(q.shape) << ".";
      e.error_message = os.str();
      return;
    }
    if (dims_after_first_must_match) {
      bool ok = q.shape.size() == e.first.shape.size();
      for (size_t i = 1; ok && i < q.shape.size(); ++i)
        ok = q.shape[i] == e.first.shape[i];
      if (!ok) {
        os << "Mismatched " << request_type_name(q.type)
           << " tensor shapes: all dimensions except the first must match "
           << "(rank " << e.first_rank << ": "
           << shape_to_string(e.first.shape) << ", rank " << rank << ": "
           << shape_to_string(q.shape) << ") for tensor " << e.first.name
           << ".";
        e.error_message = os.str();
        return;
      }
    }
    if (q.type == RequestType::BROADCAST && q.root_rank != e.first.root_rank) {
      os << "Mismatched broadcast root ranks: rank " << e.first_rank
         << " used root " << e.first.root_rank << " while rank " << rank
         << " used root " << q.root_rank << " for tensor " << e.first.name
         << ".";
      e.error_message = os.str();
      return;
    }
    bool crc_checked = q.type == RequestType::ALLTOALL ||
                       q.type == RequestType::ALLGATHER;
    if (crc_checked && q.splits_crc != 0 && e.first.splits_crc != 0 &&
        q.splits_crc != e.first.splits_crc) {
      os << "Mismatched " << request_type_name(q.type)
         << " size metadata for tensor " << e.first.name << ": rank "
         << e.first_rank << " and rank " << rank
         << " derived their splits/dim0 rows from different matrices.";
      e.error_message = os.str();
      return;
    }
    bool reduce_like = q.type == RequestType::ALLREDUCE ||
                       q.type == RequestType::ADASUM ||
                       q.type == RequestType::REDUCESCATTER;
    if (reduce_like && (q.reduce_op != e.first.reduce_op ||
                        q.prescale != e.first.prescale ||
                        q.postscale != e.first.postscale)) {
      os << "Mismatched reduce parameters for tensor " << e.first.name
         << ": rank " << e.first_rank << " used (op=" << e.first.reduce_op
         << ", prescale=" << e.first.prescale << ", postscale="
         << e.first.postscale << ") while rank " << rank << " used (op="
         << q.reduce_op << ", prescale=" << q.prescale << ", postscale="
         << q.postscale << ").";
      e.error_message = os.str();
    }
  }

  /* FuseResponses (controller.cc): pack consecutive ready responses of the
   * same fusable class under the fusion threshold into joint responses. */
  void fuse(const std::vector<const TableEntry*>& schedulable,
            ResponseList& result) {
    Response current;
    bool open = false;
    auto flush = [&]() {
      if (open) {
        result.responses.push_back(current);
        open = false;
      }
    };
    for (const TableEntry* e : schedulable) {
      const Request& q = e->first;
      ResponseType rtype = static_cast<ResponseType>(q.type);
      /* ALLGATHER left out of fusion: its response carries the per-rank
       * first dims (ragged allgatherv, collective_operations.h:143-178)
       * in recv_splits, which a joint response cannot represent per
       * tensor. */
      bool fusable = q.type == RequestType::ALLREDUCE ||
                     q.type == RequestType::ADASUM ||
                     q.type == RequestType::BROADCAST;
      int64_t bytes = q.byte_size();
      if (!fusable) {
        flush();
        Response r;
        r.type = rtype;
        r.dtype = q.dtype;
        r.root_rank = q.root_rank;
        r.total_bytes = bytes;
        r.tensor_names = {q.name};
        r.shapes = {q.shape};
        r.group_ids = {q.group_id};
        r.reduce_op = q.reduce_op;
        r.prescale = q.prescale;
        r.postscale = q.postscale;
        if (q.type == RequestType::ALLTOALL) {
          /* Negotiated recv-splits for this engine's rank: rank j sends us
           * splits_j[rank_] rows (its even share when it sent no splits) —
           * the reference's AlltoallGetRecvSplits metadata exchange
           * (collective_operations.h:219-221). */
          r.recv_splits.resize(world_size_);
          for (int32_t j = 0; j < world_size_; ++j) {
            auto sit = e->splits_by_rank.find(j);
            if (sit != e->splits_by_rank.end()) {
              r.recv_splits[j] = sit->second[rank_];
            } else {
              auto dit = e->dim0_by_rank.find(j);
              int64_t d0 = dit == e->dim0_by_rank.end() ? 0 : dit->second;
              r.recv_splits[j] =
                  static_cast<int32_t>(world_size_ ? d0 / world_size_ : 0);
            }
          }
        } else if (q.type == RequestType::ALLGATHER) {
          /* Per-rank first dims (the ragged-allgather size exchange,
           * collective_operations.h:143-178 displacement inputs): rank j
           * contributes recv_splits[j] rows; ranks with no recorded dim
           * (joined ranks) contribute zero rows. */
          r.recv_splits.resize(world_size_);
          for (int32_t j = 0; j < world_size_; ++j) {
            auto dit = e->dim0_by_rank.find(j);
            r.recv_splits[j] = dit == e->dim0_by_rank.end()
                                   ? 0
                                   : static_cast<int32_t>(dit->second);
          }
        }
        result.responses.push_back(std::move(r));
        continue;
      }
      bool joinable = open && current.type == rtype &&
                      current.dtype == q.dtype &&
                      current.root_rank == q.root_rank &&
                      current.reduce_op == q.reduce_op &&
                      current.prescale == q.prescale &&
                      current.postscale == q.postscale &&
                      current.total_bytes + bytes <= fusion_threshold_;
      if (joinable) {
        current.tensor_names.push_back(q.name);
        current.shapes.push_back(q.shape);
        current.group_ids.push_back(q.group_id);
        current.total_bytes += bytes;
      } else {
        flush();
        current = Response();
        current.type = rtype;
        current.dtype = q.dtype;
        current.root_rank = q.root_rank;
        current.reduce_op = q.reduce_op;
        current.prescale = q.prescale;
        current.postscale = q.postscale;
        current.total_bytes = bytes;
        current.tensor_names = {q.name};
        current.shapes = {q.shape};
        current.group_ids = {q.group_id};
        open = true;
      }
    }
    flush();
  }

  void complete(const std::string& name) {
    local_inflight_.erase(name);
    outstanding_.erase(name);
    /* A completed op must not leave a same-named request queued for the
     * next pop (possible when a post-timeout retry was enqueued just
     * before a straggler completed the original): that request would
     * become a ghost table entry on every rank. */
    for (auto it = pending_.begin(); it != pending_.end(); ++it) {
      if (it->name == name) {
        pending_.erase(it);
        break;
      }
    }
  }

  int32_t world_size_;
  int32_t rank_;
  int64_t fusion_threshold_;
  double stall_warn_;
  double stall_shutdown_;

  std::mutex mu_;
  std::vector<Request> pending_;
  std::set<std::string> served_this_cycle_;
  std::set<std::string> outstanding_;
  std::unordered_map<std::string, Request> local_inflight_;
  std::map<std::string, TableEntry> table_;
  std::set<int32_t> joined_ranks_;
  std::set<std::string> join_names_;
  int32_t last_joined_rank_ = -1;
  bool join_pending_ = false;
  uint64_t next_sequence_ = 0;
  std::map<int32_t, size_t> group_member_counts_;

  ResponseCache cache_;
  std::vector<Response> cache_hits_this_cycle_;

  std::vector<uint8_t> pop_buf_, resp_buf_, bits_buf_, stall_buf_;
};

}  // namespace
}  // namespace hvd

/* ------------------------------------------------------------- C API --- */

extern "C" {

hvd_engine_t hvd_engine_create(int32_t world_size, int32_t rank,
                               int64_t fusion_threshold_bytes,
                               int32_t cache_capacity,
                               double stall_warn_seconds,
                               double stall_shutdown_seconds) {
  return new hvd::Engine(world_size, rank, fusion_threshold_bytes,
                         cache_capacity, stall_warn_seconds,
                         stall_shutdown_seconds);
}

void hvd_engine_destroy(hvd_engine_t engine) {
  delete static_cast<hvd::Engine*>(engine);
}

int32_t hvd_engine_enqueue(hvd_engine_t engine, const char* name,
                           int32_t request_type, int32_t dtype,
                           int32_t element_size, const int64_t* shape,
                           int32_t ndim, int32_t root_rank, int32_t group_id,
                           const int32_t* splits, int32_t nsplits,
                           int32_t reduce_op, double prescale,
                           double postscale, int32_t splits_crc) {
  return static_cast<hvd::Engine*>(engine)->enqueue(
      name, request_type, dtype, element_size, shape, ndim, root_rank,
      group_id, splits, nsplits, reduce_op, prescale, postscale, splits_crc);
}

int32_t hvd_engine_pop_requests(hvd_engine_t engine, const uint8_t** out,
                                size_t* out_len) {
  return static_cast<hvd::Engine*>(engine)->pop_requests(out, out_len);
}

int32_t hvd_engine_ingest(hvd_engine_t engine, int32_t rank,
                          const uint8_t* data, size_t len) {
  return static_cast<hvd::Engine*>(engine)->ingest(rank, data, len);
}

int32_t hvd_engine_compute_responses(hvd_engine_t engine, const uint8_t** out,
                                     size_t* out_len) {
  return static_cast<hvd::Engine*>(engine)->compute_responses(out, out_len);
}

int32_t hvd_engine_cache_bits(hvd_engine_t engine, const uint8_t** out,
                              size_t* out_len) {
  return static_cast<hvd::Engine*>(engine)->cache_bits(out, out_len);
}

int32_t hvd_engine_commit_cache_bits(hvd_engine_t engine, const uint8_t* bits,
                                     size_t len) {
  return static_cast<hvd::Engine*>(engine)->commit_cache_bits(bits, len);
}

int32_t hvd_engine_stall_report(hvd_engine_t engine, const uint8_t** out,
                                size_t* out_len) {
  return static_cast<hvd::Engine*>(engine)->stall_report(out, out_len);
}

void hvd_engine_register_group(hvd_engine_t engine, int32_t group_id,
                               int32_t n_members) {
  static_cast<hvd::Engine*>(engine)->register_group(
      group_id, static_cast<size_t>(n_members));
}

int32_t hvd_engine_abandon(hvd_engine_t engine, const char* name) {
  return static_cast<hvd::Engine*>(engine)->abandon(name);
}

int32_t hvd_timeline_start(hvd_engine_t engine, const char* path) {
  return static_cast<hvd::Engine*>(engine)->timeline.start(path);
}

void hvd_timeline_stop(hvd_engine_t engine) {
  static_cast<hvd::Engine*>(engine)->timeline.stop();
}

void hvd_timeline_record(hvd_engine_t engine, const char* tensor,
                         const char* activity, int32_t phase,
                         int64_t timestamp_us) {
  static_cast<hvd::Engine*>(engine)->timeline.record(tensor, activity, phase,
                                                     timestamp_us);
}

int32_t hvd_engine_pending_count(hvd_engine_t engine) {
  return static_cast<hvd::Engine*>(engine)->pending_count();
}

int32_t hvd_engine_cache_size(hvd_engine_t engine) {
  return static_cast<hvd::Engine*>(engine)->cache_size();
}

int32_t hvd_engine_cache_has(hvd_engine_t engine, const char* name) {
  return static_cast<hvd::Engine*>(engine)->cache_has(name);
}

int32_t hvd_engine_join_pending(hvd_engine_t engine) {
  return static_cast<hvd::Engine*>(engine)->join_pending();
}

const char* hvd_core_version(void) { return "hvd_core 0.1.0"; }

}  /* extern "C" */
