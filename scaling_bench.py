#!/usr/bin/env python
"""Scaling-efficiency harness on a virtual device mesh — the rebuild's
analog of the reference's published scaling-efficiency metric
(``/root/reference/docs/benchmarks.rst:13-43``: 90% scaling efficiency for
ResNet-101/Inception-V3 at 512 GPUs, measured with
``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``).

Real multi-chip hardware isn't available in this environment, so this
measures what *can* be measured honestly on N virtual CPU devices that
share one physical machine:

  **Fixed total work, sharded over n devices.** All virtual devices share
  the same cores, so weak scaling (n x work on the same silicon) is
  meaningless here. Instead the total batch is held constant and sharded
  over n ∈ {1,2,4,8}; ideal step time is flat, and any rise is the
  framework's collective/partitioning overhead — the quantity scaling
  efficiency actually stresses. efficiency(n) = t(1) / t(n).

Runs the framework's real collective layer (DistributedOptimizer ->
grouped_allreduce -> traced lax.psum) in ``flat`` mode and the two-level
ICI/DCN schedule (``ops/hierarchical.py``) in ``hier`` mode.

Writes SCALING_r{N}.json and prints one JSON line per configuration.
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))


def _make_model(name: str):
    """The reference's three published scaling models, in small-input
    form (docs/benchmarks.rst:13-14 runs ResNet-101/Inception-V3/VGG-16;
    the virtual-CPU harness uses the light family members so the signal
    is collective overhead, not CPU conv time). Returns
    (model, input_side, description) — the description is derived here so
    the recorded artifact metadata cannot drift from what ran."""
    import jax.numpy as jnp

    from horovod_tpu import models as M

    if name == "resnet":
        return (M.ResNet18(num_classes=10, dtype=jnp.float32,
                           axis_name=None), 32, "ResNet18/32x32")
    if name == "vgg":
        width = 256
        return (M.VGG16(num_classes=10, dtype=jnp.float32,
                        classifier_width=width), 32,
                f"VGG16(classifier_width={width})/32x32")
    if name == "inception":
        return M.InceptionV3(num_classes=10, dtype=jnp.float32), 75, \
            "InceptionV3/75x75"
    raise ValueError(f"unknown model {name!r}")




def _build_mode(mode: str, n: int, model, side, total_batch):
    """Compile one mode's train step and build its device state."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.ops import hierarchical

    devs = jax.devices()[:n]
    rng = jax.random.PRNGKey(0)
    images = np.random.default_rng(0).standard_normal(
        (total_batch, side, side, 3), dtype=np.float32)
    labels = np.random.default_rng(1).integers(0, 10, size=(total_batch,))

    variables = model.init(rng, jnp.zeros((1, side, side, 3), jnp.float32),
                           train=False)
    params = variables["params"]
    # VGG has no batch norm; ResNet/Inception do. Eval-mode apply keeps
    # the loss generic (the harness measures collective overhead, not
    # batch-norm bookkeeping) — stats ride along untouched.
    batch_stats = dict(variables.get("batch_stats", {}))
    inner = optax.sgd(0.05, momentum=0.9)

    def loss_fn(p, batch_stats, images, labels):
        vars_in = {"params": p}
        if batch_stats:
            vars_in["batch_stats"] = batch_stats
        logits = model.apply(vars_in, images, train=False)
        one_hot = jax.nn.one_hot(labels, 10)
        loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
        return loss, batch_stats

    if mode == "flat":
        mesh = Mesh(np.array(devs), ("data",))
        tx = hvd.DistributedOptimizer(inner, axis_name="data")
        data_spec = P("data")
    elif mode == "nosync":
        # control: identical sharded execution with NO gradient sync —
        # isolates the shared-core partitioned-execution overhead from the
        # framework's collective overhead
        mesh = Mesh(np.array(devs), ("data",))
        tx = inner
        data_spec = P("data")
    elif mode == "hier":
        ici = 2 if n % 2 == 0 else 1
        mesh = Mesh(np.array(devs).reshape(n // ici, ici), ("dcn", "ici"))
        tx = inner  # grads reduced explicitly below via the two-level schedule
        data_spec = P(("dcn", "ici"))
    else:
        raise ValueError(mode)

    opt_state = tx.init(params)

    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        if mode == "hier":
            grads = jax.tree.map(
                lambda g: hierarchical.hierarchical_allreduce_traced(
                    g, "ici", "dcn", op=hvd.ReduceOp.AVERAGE), grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, new_opt, loss

    # no donation: the state is reused across interleaved timing rounds
    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P(), P()), check_vma=False))

    images = jax.device_put(images, NamedSharding(mesh, data_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, data_spec))
    rep = NamedSharding(mesh, P())
    state = dict(params=jax.device_put(params, rep),
                 batch_stats=jax.device_put(batch_stats, rep),
                 opt_state=jax.device_put(opt_state, rep))
    return {"step": step, "state": state, "images": images, "labels": labels}


def child_main(n: int, modes: list, total_batch: int, iters: int,
               model_name: str = "resnet", rounds: int | None = None) -> None:
    """Measure ALL modes interleaved in ONE process: round-robin timing
    windows so machine-load drift hits every mode equally, then paired
    per-round ratios. Round-4's separate-child design produced impossible
    ratios (flat faster than its own nosync control at n=4, 0.848 at n=8)
    from exactly that drift."""
    import jax
    import numpy as np

    import horovod_tpu as hvd

    if rounds is None:
        # variance lives at ROUND granularity (drift between adjacent
        # windows), so reps buy precision as rounds, not window length
        rounds = int(os.environ.get("SCALING_ROUNDS", "5"))
    hvd.init()  # collective layer resolves the (global) process set
    model, side, _desc = _make_model(model_name)
    built = {m: _build_mode(m, n, model, side, total_batch) for m in modes}

    def run_window(b, k):
        s = b["state"]
        t0 = time.perf_counter()
        for _ in range(k):
            # block per step: XLA-CPU's in-process rendezvous deadlocks on
            # unbounded async pile-up of collective programs
            p, bs, o, loss = b["step"](s["params"], s["batch_stats"],
                                       s["opt_state"], b["images"],
                                       b["labels"])
            jax.block_until_ready(loss)
            s.update(params=p, batch_stats=bs, opt_state=o)
        return (time.perf_counter() - t0) / k

    for b in built.values():  # compile + settle caches
        run_window(b, 2)

    per_mode = {m: [] for m in modes}
    for _ in range(rounds):
        for m in modes:  # round-robin: drift lands on every mode equally
            per_mode[m].append(run_window(built[m], max(1, iters // rounds)))

    out = {}
    for m in modes:
        arr = np.asarray(per_mode[m])
        out[m] = {"n": n, "mode": m,
                  "step_ms": round(float(np.median(arr)) * 1e3, 3),
                  "step_ms_std": round(float(arr.std()) * 1e3, 3),
                  "rounds": rounds}
    if "nosync" in modes:
        base = np.asarray(per_mode["nosync"])
        for m in modes:
            if m == "nosync":
                continue
            ratios = base / np.asarray(per_mode[m])  # paired per round
            out[m]["collective_efficiency"] = round(
                float(np.median(ratios)), 3)
            out[m]["collective_efficiency_std"] = round(
                float(ratios.std()), 3)
    for m in modes:
        print(json.dumps(out[m]))


def run_child(n: int, modes: list, total_batch: int, iters: int,
              max_devices: int, model: str = "resnet") -> list:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={max_devices}")
    env["PALLAS_AXON_POOL_IPS"] = ""  # never claim a real backend
    for k in list(env):
        if k.startswith(("HVD_", "HOROVOD_")):
            env.pop(k)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_child",
         str(n), ",".join(modes), str(total_batch), str(iters), model],
        env=env, cwd=HERE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=3600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"scaling child n={n} modes={modes} failed:\n{proc.stderr[-4000:]}")
    rows = {}
    for ln in proc.stdout.strip().splitlines():
        if not ln.startswith("{"):
            continue
        try:
            row = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if row.get("mode") in modes and row.get("n") == n:
            rows[row["mode"]] = row  # keyed: stray '{' lines can't alias
    missing = [m for m in modes if m not in rows]
    if missing:
        raise RuntimeError(
            f"scaling child n={n} produced no result rows for {missing}; "
            f"stdout tail:\n{proc.stdout[-2000:]}")
    return [rows[m] for m in modes]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--_child", nargs=5,
                        metavar=("N", "MODE", "BATCH", "ITERS", "MODEL"))
    parser.add_argument("--devices", default="1,2,4,8")
    parser.add_argument("--total-batch", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--model", default="resnet",
                        choices=("resnet", "vgg", "inception"),
                        help="the reference's three published scaling "
                             "models (docs/benchmarks.rst:13-14)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args._child:
        n, modes, batch, iters, model = args._child
        child_main(int(n), modes.split(","), int(batch), int(iters), model)
        return

    device_counts = [int(x) for x in args.devices.split(",")]
    max_devices = max(device_counts)
    results = []
    base_ms = None
    for n in device_counts:
        modes = ["flat"] if n == 1 else ["nosync", "flat", "hier"]
        for r in run_child(n, modes, args.total_batch, args.iters,
                           max_devices, args.model):
            if base_ms is None:
                base_ms = r["step_ms"]
            r["efficiency"] = round(base_ms / r["step_ms"], 3)
            if r["mode"] == "hier":
                r["note"] = ("single-host virtual mesh: both levels share "
                             "one core, so this row measures the two-level "
                             "schedule's pure overhead — there is no real "
                             "ICI/DCN asymmetry for it to exploit here")
            results.append(r)
            print(json.dumps(r))

    out = args.out or os.path.join(HERE, f"SCALING_{args.model}_r5.json")
    payload = {
        "harness": "fixed-total-work strong scaling on virtual CPU devices; "
                   "all modes of one n interleaved round-robin in ONE child "
                   "process with paired per-round ratios (machine-load "
                   "drift hits every mode equally)",
        "model": _make_model(args.model)[2],
        "total_batch": args.total_batch,
        "metric": "efficiency = t(1)/t(n), ideal 1.0; collective_efficiency "
                  "= median over paired rounds of t(nosync)/t(mode), "
                  "isolating the framework's collective overhead from the "
                  "shared-core partitioned-execution emulation overhead "
                  "(all virtual devices share one physical core here); "
                  "*_std columns are across-round standard deviations",
        "reference_target": ">=0.90 collective_efficiency, mirroring "
                            "docs/benchmarks.rst:13-14",
        "variance_note": (
            "reproducibility: on this shared-core emulation the paired "
            "ratios vary run-to-run by up to ~0.1 at n=8 depending on "
            "background load (same-day re-runs measured 0.87-0.95 for "
            "identical configs); run on an otherwise-idle machine. On "
            "real TPU ICI the gradient allreduce overlaps with backward "
            "compute, removing the overhead this proxy metric pays "
            "entirely."),
        "results": results,
    }
    with open(out, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps({"metric": "collective_efficiency_8dev_flat",
                      "value": next((r.get("collective_efficiency")
                                     for r in results
                                     if r["n"] == max_devices and r["mode"] == "flat"),
                                    None),
                      "unit": "ratio", "out": out}))


if __name__ == "__main__":
    main()
