#!/usr/bin/env python
"""Scaling-efficiency harness on a virtual device mesh — the rebuild's
analog of the reference's published scaling-efficiency metric
(``/root/reference/docs/benchmarks.rst:13-43``: 90% scaling efficiency for
ResNet-101/Inception-V3 at 512 GPUs, measured with
``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``).

Real multi-chip hardware isn't available in this environment, so this
measures what *can* be measured honestly on N virtual CPU devices that
share one physical machine:

  **Fixed total work, sharded over n devices.** All virtual devices share
  the same cores, so weak scaling (n x work on the same silicon) is
  meaningless here. Instead the total batch is held constant and sharded
  over n ∈ {1,2,4,8}; ideal step time is flat, and any rise is the
  framework's collective/partitioning overhead — the quantity scaling
  efficiency actually stresses. efficiency(n) = t(1) / t(n).

Runs the framework's real collective layer (DistributedOptimizer ->
grouped_allreduce -> traced lax.psum) in ``flat`` mode and the two-level
ICI/DCN schedule (``ops/hierarchical.py``) in ``hier`` mode.

Writes SCALING_r{N}.json and prints one JSON line per configuration.
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))


def _make_model(name: str):
    """The reference's three published scaling models, in small-input
    form (docs/benchmarks.rst:13-14 runs ResNet-101/Inception-V3/VGG-16;
    the virtual-CPU harness uses the light family members so the signal
    is collective overhead, not CPU conv time). Returns
    (model, input_side, description) — the description is derived here so
    the recorded artifact metadata cannot drift from what ran."""
    import jax.numpy as jnp

    from horovod_tpu import models as M

    if name == "resnet":
        return (M.ResNet18(num_classes=10, dtype=jnp.float32,
                           axis_name=None), 32, "ResNet18/32x32")
    if name == "vgg":
        width = 256
        return (M.VGG16(num_classes=10, dtype=jnp.float32,
                        classifier_width=width), 32,
                f"VGG16(classifier_width={width})/32x32")
    if name == "inception":
        return M.InceptionV3(num_classes=10, dtype=jnp.float32), 75, \
            "InceptionV3/75x75"
    raise ValueError(f"unknown model {name!r}")




def child_main(n: int, mode: str, total_batch: int, iters: int,
               model_name: str = "resnet") -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.ops import hierarchical

    hvd.init()  # collective layer resolves the (global) process set
    devs = jax.devices()[:n]
    # local (non-sync) batch norm, matching the reference benchmark's
    # semantics — gradient allreduce is the only cross-device traffic
    model, side, _desc = _make_model(model_name)
    rng = jax.random.PRNGKey(0)
    images = np.random.default_rng(0).standard_normal(
        (total_batch, side, side, 3), dtype=np.float32)
    labels = np.random.default_rng(1).integers(0, 10, size=(total_batch,))

    variables = model.init(rng, jnp.zeros((1, side, side, 3), jnp.float32),
                           train=False)
    params = variables["params"]
    # VGG has no batch norm; ResNet/Inception do. Eval-mode apply keeps
    # the loss generic (the harness measures collective overhead, not
    # batch-norm bookkeeping) — stats ride along untouched.
    batch_stats = dict(variables.get("batch_stats", {}))
    inner = optax.sgd(0.05, momentum=0.9)

    def loss_fn(p, batch_stats, images, labels):
        vars_in = {"params": p}
        if batch_stats:
            vars_in["batch_stats"] = batch_stats
        logits = model.apply(vars_in, images, train=False)
        one_hot = jax.nn.one_hot(labels, 10)
        loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
        return loss, batch_stats

    if mode == "flat":
        mesh = Mesh(np.array(devs), ("data",))
        tx = hvd.DistributedOptimizer(inner, axis_name="data")
        data_spec = P("data")
    elif mode == "nosync":
        # control: identical sharded execution with NO gradient sync —
        # isolates the shared-core partitioned-execution overhead from the
        # framework's collective overhead
        mesh = Mesh(np.array(devs), ("data",))
        tx = inner
        data_spec = P("data")
    elif mode == "hier":
        ici = 2 if n % 2 == 0 else 1
        mesh = Mesh(np.array(devs).reshape(n // ici, ici), ("dcn", "ici"))
        tx = inner  # grads reduced explicitly below via the two-level schedule
        data_spec = P(("dcn", "ici"))
    else:
        raise ValueError(mode)

    opt_state = tx.init(params)

    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        if mode == "hier":
            grads = jax.tree.map(
                lambda g: hierarchical.hierarchical_allreduce_traced(
                    g, "ici", "dcn", op=hvd.ReduceOp.AVERAGE), grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, new_opt, loss

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1, 2))

    images = jax.device_put(images, NamedSharding(mesh, data_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, data_spec))
    rep = NamedSharding(mesh, P())
    params = jax.device_put(params, rep)
    batch_stats = jax.device_put(batch_stats, rep)
    opt_state = jax.device_put(opt_state, rep)

    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    jax.block_until_ready(loss)

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    times.sort()
    # median-of-iters: virtual-device CPU timing is noisy
    med = times[len(times) // 2]
    print(json.dumps({"n": n, "mode": mode, "step_ms": round(med * 1e3, 3)}))


def run_child(n: int, mode: str, total_batch: int, iters: int,
              max_devices: int, model: str = "resnet") -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={max_devices}")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for k in list(env):
        if k.startswith(("HVD_", "HOROVOD_")):
            env.pop(k)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_child",
         str(n), mode, str(total_batch), str(iters), model],
        env=env, cwd=HERE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(
            f"scaling child n={n} mode={mode} failed:\n{proc.stderr[-4000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--_child", nargs=5,
                        metavar=("N", "MODE", "BATCH", "ITERS", "MODEL"))
    parser.add_argument("--devices", default="1,2,4,8")
    parser.add_argument("--total-batch", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--model", default="resnet",
                        choices=("resnet", "vgg", "inception"),
                        help="the reference's three published scaling "
                             "models (docs/benchmarks.rst:13-14)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args._child:
        n, mode, batch, iters, model = args._child
        child_main(int(n), mode, int(batch), int(iters), model)
        return

    device_counts = [int(x) for x in args.devices.split(",")]
    max_devices = max(device_counts)
    results = []
    base_ms = None
    nosync_ms = {}
    for n in device_counts:
        modes = ["flat"] if n == 1 else ["nosync", "flat", "hier"]
        for mode in modes:
            r = run_child(n, mode, args.total_batch, args.iters,
                          max_devices, args.model)
            if base_ms is None:
                base_ms = r["step_ms"]
            if mode == "nosync":
                nosync_ms[n] = r["step_ms"]
            r["efficiency"] = round(base_ms / r["step_ms"], 3)
            # collective-layer efficiency: vs the identical sharded run
            # with no gradient sync (strips the shared-core partitioned-
            # execution emulation overhead that real hardware doesn't have)
            if mode in ("flat", "hier") and n in nosync_ms:
                r["collective_efficiency"] = round(
                    nosync_ms[n] / r["step_ms"], 3)
            results.append(r)
            print(json.dumps(r))

    out = args.out or os.path.join(HERE, f"SCALING_{args.model}_r4.json")
    payload = {
        "harness": "fixed-total-work strong scaling on virtual CPU devices",
        "model": _make_model(args.model)[2],
        "total_batch": args.total_batch,
        "metric": "efficiency = t(1)/t(n), ideal 1.0; collective_efficiency "
                  "= t(nosync,n)/t(mode,n) isolates the framework's "
                  "collective overhead from the shared-core partitioned-"
                  "execution emulation overhead (all virtual devices share "
                  "one physical core here)",
        "reference_target": ">=0.90 collective_efficiency, mirroring "
                            "docs/benchmarks.rst:13-14",
        "results": results,
    }
    with open(out, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps({"metric": "collective_efficiency_8dev_flat",
                      "value": next((r.get("collective_efficiency")
                                     for r in results
                                     if r["n"] == max_devices and r["mode"] == "flat"),
                                    None),
                      "unit": "ratio", "out": out}))


if __name__ == "__main__":
    main()
