#!/usr/bin/env python
"""Scaling-efficiency harness on a virtual device mesh — the rebuild's
analog of the reference's published scaling-efficiency metric
(``/root/reference/docs/benchmarks.rst:13-43``: 90% scaling efficiency for
ResNet-101/Inception-V3 at 512 GPUs, measured with
``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``).

Real multi-chip hardware isn't available in this environment, so this
measures what *can* be measured honestly on N virtual CPU devices that
share one physical machine:

  **Fixed total work, sharded over n devices.** All virtual devices share
  the same cores, so weak scaling (n x work on the same silicon) is
  meaningless here. Instead the total batch is held constant and sharded
  over n ∈ {1,2,4,8}; ideal step time is flat, and any rise is the
  framework's collective/partitioning overhead — the quantity scaling
  efficiency actually stresses. efficiency(n) = t(1) / t(n).

Runs the framework's real collective layer (DistributedOptimizer ->
grouped_allreduce -> traced lax.psum) in ``flat`` mode and the two-level
ICI/DCN schedule (``ops/hierarchical.py``) in ``hier`` mode.

Writes SCALING_r{N}.json and prints one JSON line per configuration.
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))


def _make_model(name: str):
    """The reference's three published scaling models, in small-input
    form (docs/benchmarks.rst:13-14 runs ResNet-101/Inception-V3/VGG-16;
    the virtual-CPU harness uses the light family members so the signal
    is collective overhead, not CPU conv time). Returns
    (model, input_side, description) — the description is derived here so
    the recorded artifact metadata cannot drift from what ran."""
    import jax.numpy as jnp

    from horovod_tpu import models as M

    if name == "resnet":
        return (M.ResNet18(num_classes=10, dtype=jnp.float32,
                           axis_name=None), 32, "ResNet18/32x32")
    if name == "vgg":
        width = 256
        return (M.VGG16(num_classes=10, dtype=jnp.float32,
                        classifier_width=width), 32,
                f"VGG16(classifier_width={width})/32x32")
    if name == "inception":
        return M.InceptionV3(num_classes=10, dtype=jnp.float32), 75, \
            "InceptionV3/75x75"
    raise ValueError(f"unknown model {name!r}")




def _build_mode(mode: str, n: int, model, side, total_batch):
    """Compile one mode's train step and build its device state."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.ops import hierarchical

    devs = jax.devices()[:n]
    rng = jax.random.PRNGKey(0)
    images = np.random.default_rng(0).standard_normal(
        (total_batch, side, side, 3), dtype=np.float32)
    labels = np.random.default_rng(1).integers(0, 10, size=(total_batch,))

    variables = model.init(rng, jnp.zeros((1, side, side, 3), jnp.float32),
                           train=False)
    params = variables["params"]
    # VGG has no batch norm; ResNet/Inception do. Eval-mode apply keeps
    # the loss generic (the harness measures collective overhead, not
    # batch-norm bookkeeping) — stats ride along untouched.
    batch_stats = dict(variables.get("batch_stats", {}))
    inner = optax.sgd(0.05, momentum=0.9)

    def loss_fn(p, batch_stats, images, labels):
        vars_in = {"params": p}
        if batch_stats:
            vars_in["batch_stats"] = batch_stats
        logits = model.apply(vars_in, images, train=False)
        one_hot = jax.nn.one_hot(labels, 10)
        loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
        return loss, batch_stats

    if mode == "flat":
        mesh = Mesh(np.array(devs), ("data",))
        tx = hvd.DistributedOptimizer(inner, axis_name="data")
        data_spec = P("data")
    elif mode == "nosync":
        # control: identical sharded execution with NO gradient sync —
        # isolates the shared-core partitioned-execution overhead from the
        # framework's collective overhead
        mesh = Mesh(np.array(devs), ("data",))
        tx = inner
        data_spec = P("data")
    elif mode == "hier":
        ici = 2 if n % 2 == 0 else 1
        mesh = Mesh(np.array(devs).reshape(n // ici, ici), ("dcn", "ici"))
        tx = inner  # grads reduced explicitly below via the two-level schedule
        data_spec = P(("dcn", "ici"))
    else:
        raise ValueError(mode)

    opt_state = tx.init(params)

    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        if mode == "hier":
            grads = jax.tree.map(
                lambda g: hierarchical.hierarchical_allreduce_traced(
                    g, "ici", "dcn", op=hvd.ReduceOp.AVERAGE), grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, new_opt, loss

    # no donation: the state is reused across interleaved timing rounds
    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P(), P()), check_vma=False))

    images = jax.device_put(images, NamedSharding(mesh, data_spec))
    labels = jax.device_put(labels, NamedSharding(mesh, data_spec))
    rep = NamedSharding(mesh, P())
    state = dict(params=jax.device_put(params, rep),
                 batch_stats=jax.device_put(batch_stats, rep),
                 opt_state=jax.device_put(opt_state, rep))
    return {"step": step, "state": state, "images": images, "labels": labels}


def child_main(n: int, modes: list, total_batch: int, iters: int,
               model_name: str = "resnet", rounds: int | None = None) -> None:
    """Measure ALL modes interleaved in ONE process: round-robin timing
    windows so machine-load drift hits every mode equally, then paired
    per-round ratios. Round-4's separate-child design produced impossible
    ratios (flat faster than its own nosync control at n=4, 0.848 at n=8)
    from exactly that drift."""
    import jax
    import numpy as np

    import horovod_tpu as hvd

    if rounds is None:
        # variance lives at ROUND granularity (drift between adjacent
        # windows), so reps buy precision as rounds, not window length
        rounds = int(os.environ.get("SCALING_ROUNDS", "5"))
    hvd.init()  # collective layer resolves the (global) process set
    model, side, _desc = _make_model(model_name)
    built = {m: _build_mode(m, n, model, side, total_batch) for m in modes}

    def run_window(b, k):
        s = b["state"]
        t0 = time.perf_counter()
        for _ in range(k):
            # block per step: XLA-CPU's in-process rendezvous deadlocks on
            # unbounded async pile-up of collective programs
            p, bs, o, loss = b["step"](s["params"], s["batch_stats"],
                                       s["opt_state"], b["images"],
                                       b["labels"])
            jax.block_until_ready(loss)
            s.update(params=p, batch_stats=bs, opt_state=o)
        return (time.perf_counter() - t0) / k

    for b in built.values():  # compile + settle caches
        run_window(b, 2)

    per_mode = {m: [] for m in modes}
    for _ in range(rounds):
        for m in modes:  # round-robin: drift lands on every mode equally
            per_mode[m].append(run_window(built[m], max(1, iters // rounds)))

    out = {}
    for m in modes:
        arr = np.asarray(per_mode[m])
        out[m] = {"n": n, "mode": m,
                  "step_ms": round(float(np.median(arr)) * 1e3, 3),
                  "step_ms_std": round(float(arr.std()) * 1e3, 3),
                  "rounds": rounds}
    if "nosync" in modes:
        base = np.asarray(per_mode["nosync"])
        for m in modes:
            if m == "nosync":
                continue
            ratios = base / np.asarray(per_mode[m])  # paired per round
            out[m]["collective_efficiency"] = round(
                float(np.median(ratios)), 3)
            out[m]["collective_efficiency_std"] = round(
                float(ratios.std()), 3)
    for m in modes:
        print(json.dumps(out[m]))


def _build_composed_lane(lane: str, total_batch: int, seq: int):
    """Compile one composed-parallelism lane's TransformerLM train step.

    Lanes (all world=8, float32 so the parity gates below are tight):

    * ``dp``        — pure data parallel: 1-D ``data`` mesh, flat sync.
    * ``dpsp``      — DP x SP: ``dcn=2 x ici_dp=2 x seq=2`` composed mesh,
                      ulysses attention over ``seq``, engine sync two-level
                      over the data axes only (``DistributedOptimizer``
                      ``mesh_spec`` path). Ulysses reshards without changing
                      FLOPs, so the ideal step-time ratio vs ``dp`` is 1.0.
    * ``dpep``      — DP x EP: ``dcn=2 x ici_dp=2 x expert=2``, MoE FFN over
                      ``expert``, two-level data-axis sync.
    * ``dpep_flat`` — the ``dpep`` control: identical model and mesh shape
                      but ONE flat ``data`` axis (``data=4 x expert=2``) and
                      flat sync — isolates the two-level schedule's cost on
                      a composed mesh (ideal ratio 1.0).

    The model-axis gradient reduction (pmean over seq/expert) belongs to
    the SCHEDULE and runs before ``tx.update``; the engine's collective
    then reduces only over the data axes — the composed-mesh contract
    (docs/mesh.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu import parallel
    from horovod_tpu.models import TransformerConfig, TransformerLM

    base = dict(vocab_size=128, num_layers=2, num_heads=4, d_model=128,
                d_ff=256, max_seq_len=seq, dtype=jnp.float32)
    moe = lane in ("dpep", "dpep_flat")
    if moe:
        cfg = TransformerConfig(**base, moe_experts=2, moe_axis="expert")
    elif lane == "dpsp":
        cfg = TransformerConfig(**base, attn_mode="ulysses", seq_axis="seq")
    else:
        cfg = TransformerConfig(**base)
    model = TransformerLM(cfg)

    if lane == "dp":
        mesh = parallel.mesh_for_axes(("data",), (8,))
        tx = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9),
                                      axis_name="data")
        tok_spec, model_axis = P("data"), None
    elif lane == "dpsp":
        lay = parallel.layout((("seq", 2),), ici_size=4)
        mesh = parallel.composed_mesh(lay)
        tx = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9),
                                      mesh_spec=lay)
        tok_spec, model_axis = lay.batch_spec("seq"), "seq"
    elif lane == "dpep":
        lay = parallel.layout((("expert", 2),), ici_size=4)
        mesh = parallel.composed_mesh(lay)
        tx = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9),
                                      mesh_spec=lay)
        tok_spec, model_axis = lay.batch_spec(), "expert"
    elif lane == "dpep_flat":
        mesh = parallel.mesh_for_axes(("data", "expert"), (4, 2))
        tx = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9),
                                      axis_name="data")
        tok_spec, model_axis = P("data"), "expert"
    else:
        raise ValueError(lane)
    all_axes = mesh.axis_names

    def loss_fn(p, tokens, targets):
        if moe:
            logits, inter = model.apply({"params": p}, tokens,
                                        mutable=["intermediates"])
            aux = sum(jnp.sum(a) for a in
                      jax.tree_util.tree_leaves(inter["intermediates"]))
        else:
            logits, aux = model.apply({"params": p}, tokens), 0.0
        ce = -jnp.mean(jnp.take_along_axis(
            jax.nn.log_softmax(logits), targets[..., None], -1))
        return ce + 0.01 * aux

    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        if model_axis is not None:
            # schedule-owned reduction over the model axis; the engine's
            # sync below never touches it
            grads = jax.tree.map(lambda g: lax.pmean(g, model_axis), grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_opt,
                lax.pmean(loss, all_axes))

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), tok_spec, tok_spec),
        out_specs=(P(), P(), P()), check_vma=False))

    rng = np.random.default_rng(7)
    tokens = rng.integers(0, cfg.vocab_size, size=(total_batch, seq))
    targets = np.roll(tokens, -1, axis=1)  # precomputed globally: local
    # roll would wrap within a sequence SHARD in the dpsp lane
    rep = NamedSharding(mesh, P())
    # identical init params per model family: the dense lanes share one
    # tree and the MoE lanes share another, so trajectories are comparable
    init_model = TransformerLM(dataclasses_replace_full(cfg))
    params = init_model.init(jax.random.PRNGKey(0),
                             jnp.asarray(tokens[:1]))["params"]
    opt_state = tx.init(params)
    return {
        "step": step, "mesh": mesh, "moe": moe,
        "state": dict(params=jax.device_put(params, rep),
                      opt_state=jax.device_put(opt_state, rep)),
        "tokens": jax.device_put(tokens, NamedSharding(mesh, tok_spec)),
        "targets": jax.device_put(targets, NamedSharding(mesh, tok_spec)),
    }


def dataclasses_replace_full(cfg):
    """Init-time twin of a lane config: same params, ``full`` attention
    (attn_mode never changes the param tree, and init never routes, so
    every lane of one model family inits to IDENTICAL trees)."""
    import dataclasses
    return dataclasses.replace(cfg, attn_mode="full")


def _composed_sync_bit_parity(composed_lane: str):
    """Bit-exactness gate for the composed gradient sync, in the
    exactness domain: integer-valued float32 contributions (every
    reduction order sums them exactly, and AVERAGE's divisors here are
    powers of two, which are exact in binary fp) — so the composed
    schedule (pmean over the model axis + two-level over the data axes)
    must match the pure-DP flat pmean over one 8-wide axis BIT FOR BIT.
    Any double-count, wrong-axis reduction, scatter-padding or scale bug
    still breaks equality in this domain; generic-float data would add
    ~1-ulp association noise and hide nothing extra. Shapes include an
    odd length (33) so the two-level path's pad-to-ici_dp logic is
    exercised."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import parallel
    from horovod_tpu.ops.reduce_ops import ReduceOp

    model_axis = {"dpsp": "seq", "dpep": "expert"}[composed_lane]
    lay = parallel.layout(((model_axis, 2),), ici_size=4)
    mesh_c = parallel.composed_mesh(lay)
    mesh_f = parallel.mesh_for_axes(("data",), (8,))
    shapes = [(33,), (4, 5), (16,)]

    def contrib(r):
        return [(jnp.arange(np.prod(s), dtype=jnp.float32).reshape(s)
                 * 3.0 + r * 7.0) for s in shapes]

    def composed_fn():
        d = lax.axis_index("dcn")
        i = lax.axis_index("ici_dp")
        m = lax.axis_index(model_axis)
        r = ((d * lay.ici_dp) + i) * 2 + m  # global rank, dcn-major
        xs = [lax.pmean(x, model_axis) for x in contrib(r)]
        return parallel.sync_gradients(xs, lay, op=ReduceOp.AVERAGE)

    def flat_fn():
        r = lax.axis_index("data")
        return [lax.pmean(x, "data") for x in contrib(r)]

    got = jax.jit(jax.shard_map(composed_fn, mesh=mesh_c, in_specs=(),
                                out_specs=P(), check_vma=False))()
    want = jax.jit(jax.shard_map(flat_fn, mesh=mesh_f, in_specs=(),
                                 out_specs=P(), check_vma=False))()
    return all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(got, want))


def _grouped_two_level_parity():
    """world=8 eager ``grouped_allreduce``: two-level (ICI-then-DCN,
    ``HVD_HIERARCHICAL_ALLREDUCE=1``, island=4) vs flat — bitwise on
    integer-valued float32 (exactness domain, see above) plus the max
    relative error on gaussian data (association noise only, ~1 ulp)."""
    import numpy as np

    import horovod_tpu as hvd

    rng = np.random.default_rng(3)
    n = hvd.size()
    ints = [np.float32((rng.integers(-500, 500, size=s)))
            for s in [(33,), (8, 3)]]
    gauss = [np.float32(rng.standard_normal(s)) for s in [(33,), (8, 3)]]

    def run(two_level):
        os.environ["HVD_HIERARCHICAL_ALLREDUCE"] = "1" if two_level else "0"
        os.environ["HVD_HIERARCHICAL_ICI_SIZE"] = "4"
        per_int = [hvd.per_rank([x * 1.0 + r for r in range(n)])
                   for x in ints]
        per_g = [hvd.per_rank([x * (1.0 + 0.01 * r) for r in range(n)])
                 for x in gauss]
        oi = hvd.grouped_allreduce(per_int, op=hvd.ReduceOp.SUM)
        og = hvd.grouped_allreduce(per_g, op=hvd.ReduceOp.SUM)
        return ([np.asarray(t) for t in oi], [np.asarray(t) for t in og])

    try:
        flat_i, flat_g = run(two_level=False)
        two_i, two_g = run(two_level=True)
    finally:
        os.environ.pop("HVD_HIERARCHICAL_ALLREDUCE", None)
        os.environ.pop("HVD_HIERARCHICAL_ICI_SIZE", None)
    bitwise = all(np.array_equal(a, b) for a, b in zip(flat_i, two_i))
    rel = max(float(np.max(np.abs(a - b) / (np.abs(a) + 1e-6)))
              for a, b in zip(flat_g, two_g))
    return bitwise, rel


def composed_child_main(total_batch: int, iters: int, seq: int,
                        rounds: int | None = None) -> None:
    """All four composed lanes in ONE process: numerics gates first, then
    interleaved round-robin timing with paired per-round ratios (same
    drift rationale as :func:`child_main`)."""
    import jax
    import numpy as np

    import horovod_tpu as hvd

    if rounds is None:
        rounds = int(os.environ.get("SCALING_ROUNDS", "5"))
    hvd.init()
    lanes = ["dp", "dpsp", "dpep_flat", "dpep"]
    built = {m: _build_composed_lane(m, total_batch, seq) for m in lanes}

    # -- numerics gates (before timing mutates the states) ---------------
    numerics = {
        "dpsp_sync_bitwise": _composed_sync_bit_parity("dpsp"),
        "dpep_sync_bitwise": _composed_sync_bit_parity("dpep"),
    }
    bitwise, rel = _grouped_two_level_parity()
    numerics["grouped_two_level_bitwise"] = bitwise
    numerics["grouped_two_level_gauss_max_rel"] = float(f"{rel:.3e}")

    def run_steps(b, k, record=None):
        s = b["state"]
        t0 = time.perf_counter()
        for _ in range(k):
            p, o, loss = b["step"](s["params"], s["opt_state"],
                                   b["tokens"], b["targets"])
            jax.block_until_ready(loss)
            s.update(params=p, opt_state=o)
            if record is not None:
                record.append(float(np.ravel(np.asarray(loss))[0]))
        return (time.perf_counter() - t0) / k

    # -- trajectory parity: identical inits, 4 recorded steps ------------
    traj = {m: [] for m in lanes}
    for m in lanes:
        run_steps(built[m], 4, record=traj[m])
    sp = np.asarray(traj["dpsp"])
    dp = np.asarray(traj["dp"])
    ep = np.asarray(traj["dpep"])
    epf = np.asarray(traj["dpep_flat"])
    numerics["dpsp_traj_max_rel"] = float(
        f"{np.max(np.abs(sp - dp) / np.abs(dp)):.3e}")
    # dp vs dpsp: same math, different schedule (ulysses reshard + token
    # grouping + sync association) — float32 keeps this at ulp scale
    numerics["dpsp_traj_ok"] = bool(np.allclose(sp, dp, rtol=1e-4,
                                                atol=1e-6))
    # dpep vs its flat control: identical compute, only the data-axis
    # sync schedule differs
    numerics["dpep_traj_max_rel"] = float(
        f"{np.max(np.abs(ep - epf) / np.abs(epf)):.3e}")
    numerics["dpep_traj_ok"] = bool(np.allclose(ep, epf, rtol=5e-5,
                                                atol=1e-7))
    numerics["row"] = "composed_numerics"
    print(json.dumps(numerics))

    # -- timing: round-robin windows, paired per-round ratios ------------
    for b in built.values():
        run_steps(b, 2)
    per = {m: [] for m in lanes}
    for _ in range(rounds):
        for m in lanes:
            per[m].append(run_steps(built[m], max(1, iters // rounds)))
    eff_sp = np.asarray(per["dp"]) / np.asarray(per["dpsp"])
    eff_ep = np.asarray(per["dpep_flat"]) / np.asarray(per["dpep"])
    for m in lanes:
        arr = np.asarray(per[m])
        row = {"row": "composed_lane", "lane": m,
               "step_ms": round(float(np.median(arr)) * 1e3, 3),
               "step_ms_std": round(float(arr.std()) * 1e3, 3),
               "rounds": rounds}
        if m == "dpsp":
            row["per_axis_efficiency"] = round(float(np.median(eff_sp)), 3)
            row["per_axis_efficiency_std"] = round(float(eff_sp.std()), 3)
        if m == "dpep":
            row["per_axis_efficiency"] = round(float(np.median(eff_ep)), 3)
            row["per_axis_efficiency_std"] = round(float(eff_ep.std()), 3)
        print(json.dumps(row))


def run_composed_child(total_batch: int, iters: int, seq: int) -> dict:
    """Fresh-process composed run (8 virtual devices); returns
    {lane rows..., numerics row}."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["PALLAS_AXON_POOL_IPS"] = ""
    for k in list(env):
        if k.startswith(("HVD_", "HOROVOD_")):
            env.pop(k)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_composed-child",
         str(total_batch), str(iters), str(seq)],
        env=env, cwd=HERE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=3600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"composed child failed:\n{proc.stderr[-4000:]}")
    rows = {}
    for ln in proc.stdout.strip().splitlines():
        if not ln.startswith("{"):
            continue
        try:
            row = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if row.get("row") == "composed_numerics":
            rows["numerics"] = row
        elif row.get("row") == "composed_lane":
            rows[row["lane"]] = row
    missing = [k for k in ("numerics", "dp", "dpsp", "dpep", "dpep_flat")
               if k not in rows]
    if missing:
        raise RuntimeError(
            f"composed child produced no rows for {missing}; stdout "
            f"tail:\n{proc.stdout[-2000:]}")
    return rows


def composed_main(args) -> None:
    rows = run_composed_child(args.total_batch, args.iters, args.seq)
    num = rows["numerics"]
    eff_sp = rows["dpsp"]["per_axis_efficiency"]
    eff_ep = rows["dpep"]["per_axis_efficiency"]
    out = args.out or os.path.join(HERE, "SCALING_composed_r17.json")
    payload = {
        "harness": "composed-parallelism lanes (TransformerLM, float32, "
                   "world=8 virtual CPU devices) interleaved round-robin "
                   "in ONE child with paired per-round ratios",
        "lanes": {m: rows[m] for m in ("dp", "dpsp", "dpep_flat", "dpep")},
        "numerics": num,
        "metric": "per_axis_efficiency(dpsp) = median t(dp)/t(dpsp) — "
                  "ulysses reshards without changing FLOPs so ideal is "
                  "1.0; per_axis_efficiency(dpep) = median "
                  "t(dpep_flat)/t(dpep), the two-level schedule's cost on "
                  "the composed mesh, ideal 1.0. Bitwise gates run in the "
                  "exactness domain (integer-valued float32 + power-of-two "
                  "divisors: every correct reduction order is exact, so "
                  "composed-vs-flat must agree bit for bit; generic floats "
                  "would only add ~1-ulp association noise). Trajectory "
                  "parity is paired per-step loss agreement at float32.",
        "gates": {"per_axis_efficiency_floor": 0.80,
                  "bitwise": ["dpsp_sync_bitwise", "dpep_sync_bitwise",
                              "grouped_two_level_bitwise"],
                  "trajectory": ["dpsp_traj_ok", "dpep_traj_ok"]},
    }
    with open(out, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps({
        "metric": "composed_dpsp_per_axis_efficiency", "value": eff_sp,
        "unit": "ratio", "dpep_per_axis_efficiency": eff_ep,
        "dpsp_sync_bitwise": num["dpsp_sync_bitwise"],
        "dpep_sync_bitwise": num["dpep_sync_bitwise"],
        "grouped_two_level_bitwise": num["grouped_two_level_bitwise"],
        "dpsp_traj_ok": num["dpsp_traj_ok"],
        "dpep_traj_ok": num["dpep_traj_ok"],
        "dpsp_traj_max_rel": num["dpsp_traj_max_rel"],
        "out": out}))


def run_child(n: int, modes: list, total_batch: int, iters: int,
              max_devices: int, model: str = "resnet") -> list:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={max_devices}")
    env["PALLAS_AXON_POOL_IPS"] = ""  # never claim a real backend
    for k in list(env):
        if k.startswith(("HVD_", "HOROVOD_")):
            env.pop(k)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_child",
         str(n), ",".join(modes), str(total_batch), str(iters), model],
        env=env, cwd=HERE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=3600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"scaling child n={n} modes={modes} failed:\n{proc.stderr[-4000:]}")
    rows = {}
    for ln in proc.stdout.strip().splitlines():
        if not ln.startswith("{"):
            continue
        try:
            row = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if row.get("mode") in modes and row.get("n") == n:
            rows[row["mode"]] = row  # keyed: stray '{' lines can't alias
    missing = [m for m in modes if m not in rows]
    if missing:
        raise RuntimeError(
            f"scaling child n={n} produced no result rows for {missing}; "
            f"stdout tail:\n{proc.stdout[-2000:]}")
    return [rows[m] for m in modes]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--_child", nargs=5,
                        metavar=("N", "MODE", "BATCH", "ITERS", "MODEL"))
    parser.add_argument("--_composed-child", nargs=3, dest="_composed_child",
                        metavar=("BATCH", "ITERS", "SEQ"))
    parser.add_argument("--composed", action="store_true",
                        help="composed-parallelism mode: TransformerLM "
                             "DP x SP and DP x EP lanes on one hierarchical "
                             "world=8 mesh vs the pure-DP lane (ISSUE 17)")
    parser.add_argument("--seq", type=int, default=64,
                        help="sequence length for --composed")
    parser.add_argument("--devices", default="1,2,4,8")
    parser.add_argument("--total-batch", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--model", default="resnet",
                        choices=("resnet", "vgg", "inception"),
                        help="the reference's three published scaling "
                             "models (docs/benchmarks.rst:13-14)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args._child:
        n, modes, batch, iters, model = args._child
        child_main(int(n), modes.split(","), int(batch), int(iters), model)
        return
    if args._composed_child:
        batch, iters, seq = args._composed_child
        composed_child_main(int(batch), int(iters), int(seq))
        return
    if args.composed:
        composed_main(args)
        return

    device_counts = [int(x) for x in args.devices.split(",")]
    max_devices = max(device_counts)
    results = []
    base_ms = None
    for n in device_counts:
        modes = ["flat"] if n == 1 else ["nosync", "flat", "hier"]
        for r in run_child(n, modes, args.total_batch, args.iters,
                           max_devices, args.model):
            if base_ms is None:
                base_ms = r["step_ms"]
            r["efficiency"] = round(base_ms / r["step_ms"], 3)
            if r["mode"] == "hier":
                r["note"] = ("single-host virtual mesh: both levels share "
                             "one core, so this row measures the two-level "
                             "schedule's pure overhead — there is no real "
                             "ICI/DCN asymmetry for it to exploit here")
            results.append(r)
            print(json.dumps(r))

    out = args.out or os.path.join(HERE, f"SCALING_{args.model}_r5.json")
    payload = {
        "harness": "fixed-total-work strong scaling on virtual CPU devices; "
                   "all modes of one n interleaved round-robin in ONE child "
                   "process with paired per-round ratios (machine-load "
                   "drift hits every mode equally)",
        "model": _make_model(args.model)[2],
        "total_batch": args.total_batch,
        "metric": "efficiency = t(1)/t(n), ideal 1.0; collective_efficiency "
                  "= median over paired rounds of t(nosync)/t(mode), "
                  "isolating the framework's collective overhead from the "
                  "shared-core partitioned-execution emulation overhead "
                  "(all virtual devices share one physical core here); "
                  "*_std columns are across-round standard deviations",
        "reference_target": ">=0.90 collective_efficiency, mirroring "
                            "docs/benchmarks.rst:13-14",
        "variance_note": (
            "reproducibility: on this shared-core emulation the paired "
            "ratios vary run-to-run by up to ~0.1 at n=8 depending on "
            "background load (same-day re-runs measured 0.87-0.95 for "
            "identical configs); run on an otherwise-idle machine. On "
            "real TPU ICI the gradient allreduce overlaps with backward "
            "compute, removing the overhead this proxy metric pays "
            "entirely."),
        "results": results,
    }
    with open(out, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps({"metric": "collective_efficiency_8dev_flat",
                      "value": next((r.get("collective_efficiency")
                                     for r in results
                                     if r["n"] == max_devices and r["mode"] == "flat"),
                                    None),
                      "unit": "ratio", "out": out}))


if __name__ == "__main__":
    main()
